"""Cold-tier segment store tests (store/archive).

The acceptance drive: ingest 4x ring capacity on CPU, then prove trace
fetch, get_trace_ids, dependencies, and duration quantiles over the
FULL time range match the memory-store oracle exactly — including
spans long evicted from the device ring — with the obs counters
showing segments written, compactions, and zone-map pruning actually
happening.
"""

import numpy as np
import pytest

from zipkin_tpu.models.span import (
    Annotation,
    BinaryAnnotation,
    Endpoint,
    Span,
)
from zipkin_tpu.store.archive import (
    ArchiveParams,
    Segment,
    TieredSpanStore,
    merge_segments,
)
from zipkin_tpu.store.archive import sketches as SK
from zipkin_tpu.store.device import StoreConfig
from zipkin_tpu.store.memory import InMemorySpanStore
from zipkin_tpu.store.tpu import TpuSpanStore
from zipkin_tpu.testing.conformance import (
    conformance_test_names,
    run_conformance_test,
)

WEB = Endpoint(0x01010101, 80, "web")
API = Endpoint(0x02020202, 80, "api")
DB = Endpoint(0x03030303, 80, "db")

# Small rings so 4x capacity is cheap on CPU; the annotation ring is
# deliberately tight relative to the span ring (each rpc span carries
# 5 annotation rows) so the ANNOTATION ring is the capture trigger —
# the subtle case where side rows evict before their span row.
CFG = StoreConfig(
    capacity=1 << 8, ann_capacity=1 << 10, bann_capacity=1 << 9,
    max_services=16, max_span_names=64, max_annotation_values=128,
    max_binary_keys=32, cms_width=1 << 9, hll_p=6,
    quantile_buckets=256,
)
PARAMS = ArchiveParams.for_config(
    CFG, compact_fanin=2, small_span_limit=CFG.capacity,
    bloom_bits=1 << 12, cms_width=1 << 10, hll_p=6,
)


def rpc(tid, sid, parent, client_ep, server_ep, t0, t1, name="call",
        extra_ann=None, bann=None):
    anns = [
        Annotation(t0, "cs", client_ep),
        Annotation(t0 + 1, "sr", server_ep),
        Annotation(t1 - 1, "ss", server_ep),
        Annotation(t1, "cr", client_ep),
    ]
    if extra_ann:
        anns.append(extra_ann)
    return Span(tid, name, sid, parent, tuple(anns), tuple(bann or ()))


def make_trace(tid):
    """web->api root + api->db child, deterministic timings; every 3rd
    trace carries a custom annotation and a binary annotation."""
    base = 1_000 + 100 * tid
    spans = [
        rpc(tid, 10 * tid + 1, None, WEB, API, base, base + 50,
            name=("index" if tid % 2 else "other"),
            extra_ann=(Annotation(base + 7, "boom", API)
                       if tid % 3 == 0 else None),
            bann=([BinaryAnnotation("k", b"v%d" % (tid % 4), host=API)]
                  if tid % 3 == 0 else None)),
        rpc(tid, 10 * tid + 2, 10 * tid + 1, API, DB, base + 5,
            base + 30, name="lookup"),
    ]
    return spans


def build_tiered(n_traces):
    hot = TpuSpanStore(CFG)
    tiered = TieredSpanStore(hot, params=PARAMS)
    oracle = InMemorySpanStore()
    batch = []
    for tid in range(1, n_traces + 1):
        batch.extend(make_trace(tid))
        if len(batch) >= 64:
            tiered.apply(batch)
            oracle.apply(batch)
            batch = []
    if batch:
        tiered.apply(batch)
        oracle.apply(batch)
    return tiered, oracle


class TestSketches:
    def test_bloom_no_false_negatives_and_merge(self):
        a = SK.bloom_init(1 << 10)
        b = SK.bloom_init(1 << 10)
        keys_a = np.arange(1, 200, dtype=np.int64) * 7919
        keys_b = np.arange(200, 400, dtype=np.int64) * 104729
        SK.bloom_add(a, keys_a)
        SK.bloom_add(b, keys_b)
        m = SK.bloom_merge(a, b)
        for k in list(keys_a[:20]) + list(keys_b[:20]):
            assert SK.bloom_contains(m, int(k))

    def test_cms_zero_proves_absence(self):
        c = SK.cms_init(4, 1 << 8)
        SK.cms_add(c, np.asarray([5, 5, 9], np.int64))
        assert SK.cms_query(c, 5) >= 2
        assert SK.cms_query(c, 9) >= 1
        # A key never added can only read >0 through collisions in
        # EVERY row; at this load the min over 4 rows is 0.
        absent = [k for k in range(1000, 1100)
                  if SK.cms_query(c, k) == 0]
        assert absent  # pruning power exists

    def test_hll_estimate_tracks_cardinality(self):
        h = SK.hll_init(8)
        SK.hll_add(h, np.arange(1, 1001, dtype=np.int64) * 2654435761)
        est = SK.hll_estimate(h)
        assert 800 <= est <= 1200

    def test_hist_matches_quantiles_host(self):
        from zipkin_tpu.ops.quantile import quantiles_host

        gamma = PARAMS.hist_gamma
        counts = np.zeros(256, np.int64)
        vals = np.asarray([10, 100, 1000, 10_000] * 25, np.int64)
        SK.hist_add(counts, vals, gamma)
        q = quantiles_host(counts, gamma, 1.0, [0.5])
        assert 90 <= q[0] <= 1100  # within the sketch's relative bound


class TestSegmentFormat:
    @pytest.fixture(scope="class")
    def built(self):
        tiered, _ = build_tiered(4 * CFG.capacity // 2)
        segs = tiered.archive.snapshot()
        assert segs
        return tiered, segs[0]

    def test_bytes_roundtrip_bit_exact(self, built):
        _, seg = built
        twin = Segment.from_bytes(seg.to_bytes())
        b1, g1 = seg.decode()
        b2, g2 = twin.decode()
        assert (g1 == g2).all()
        for col in type(b1).SPAN_COLUMNS:
            assert (getattr(b1, col) == getattr(b2, col)).all(), col
        assert twin.zone.service_ids == seg.zone.service_ids
        assert (twin.zone.key_cms == seg.zone.key_cms).all()
        assert (twin.zone.trace_bloom == seg.zone.trace_bloom).all()
        assert twin.dict_sizes == seg.dict_sizes

    def test_compression_actually_compresses(self, built):
        _, seg = built
        assert seg.comp_bytes < seg.raw_bytes / 2

    def test_merge_zone_is_monoidal(self, built):
        tiered, _ = built
        segs = tiered.archive.snapshot()
        if len(segs) < 2:
            pytest.skip("compaction already folded everything")
        merged = merge_segments(999, segs[:2])
        assert merged.n_spans == segs[0].n_spans + segs[1].n_spans
        assert merged.gid_lo == min(s.gid_lo for s in segs[:2])
        assert merged.gid_hi == max(s.gid_hi for s in segs[:2])
        # Anything either part may contain, the merge may contain.
        b0, g0 = segs[0].decode()
        for tid in np.unique(b0.trace_id)[:10]:
            assert merged.zone.may_contain_trace(int(tid))


class TestCaptureInvariants:
    def test_contiguous_coverage_no_gaps(self):
        """Segments tile [0, captured_upto) without gaps or overlap —
        nothing evicted was ever dropped."""
        tiered, _ = build_tiered(4 * CFG.capacity // 2)
        segs = tiered.archive.snapshot()
        assert segs, "4x ring turnover must have captured"
        assert segs[0].gid_lo == 0
        for a, b in zip(segs, segs[1:]):
            assert a.gid_hi == b.gid_lo
        assert segs[-1].gid_hi == tiered.hot._cap_upto
        assert sum(s.n_spans for s in segs) == tiered.hot._cap_upto

    def test_captured_spans_are_complete(self):
        """The annotation ring laps ~2.5x faster than the span ring at
        these shapes; the three-ring trigger must capture BEFORE side
        rows evict, so every cold span decodes with its full
        annotation set (the oracle comparison covers content)."""
        tiered, oracle = build_tiered(4 * CFG.capacity // 2)
        seg = tiered.archive.snapshot()[0]
        _, _, spans = tiered.archive.decoded(seg)
        by_key = {}
        for s in oracle.spans:
            by_key[(s.trace_id, s.id)] = s
        for s in spans[:200]:
            assert s == by_key[(s.trace_id, s.id)]


class TestCaptureHardening:
    def test_annotation_heavy_chained_writes_stay_complete(self):
        """Chained multi-chunk launches are bounded by HALF of every
        ring: without the annotation budget, four 64-span chunks of
        32-annotation spans (8192 ann rows) would chain into ONE
        launch over a 2048-row annotation ring — overwriting their own
        side rows mid-launch where no capture hook can run. Evicted
        spans must still decode with their full annotation sets."""
        cfg = StoreConfig(
            capacity=1 << 9, ann_capacity=1 << 11,
            bann_capacity=1 << 9, max_services=8, max_span_names=16,
            max_annotation_values=64, max_binary_keys=8,
            cms_width=1 << 8, hll_p=6, quantile_buckets=128,
        )
        hot = TpuSpanStore(cfg)
        tiered = TieredSpanStore(hot, params=ArchiveParams.for_config(
            cfg, compact_fanin=2, small_span_limit=cfg.capacity,
            bloom_bits=1 << 12, cms_width=1 << 9, hll_p=6))
        oracle = InMemorySpanStore()
        n = 2 * cfg.capacity
        spans = [
            Span(tid, "fat", tid, None, tuple(
                [Annotation(1000 + 100 * tid, "sr", API)]
                + [Annotation(1000 + 100 * tid + i, "custom", API)
                   for i in range(31)]
            ), ())
            for tid in range(1, n + 1)
        ]
        for i in range(0, n, 256):
            tiered.apply(spans[i:i + 256])
            oracle.apply(spans[i:i + 256])
        assert tiered.counters()["archive_segments_written"] >= 1
        for tid in (1, 2, n // 2, n):
            assert (tiered.get_spans_by_trace_ids([tid])
                    == oracle.get_spans_by_trace_ids([tid])), tid

    def test_transient_pull_failure_is_retried_not_skipped(self,
                                                          monkeypatch):
        """The capture clocks advance only AFTER the pull + seal
        succeed: a transient device error must leave the window
        uncaptured-but-resident so the retried write captures it —
        stamping first would skip those gids forever."""
        hot = TpuSpanStore(CFG)
        tiered = TieredSpanStore(hot, params=PARAMS)
        oracle = InMemorySpanStore()
        real_pull = TpuSpanStore._pull_evicted_rows
        state = {"fail": 1}

        def flaky(self, *a, **kw):
            if state["fail"] > 0:
                state["fail"] -= 1
                raise TimeoutError("simulated wedged capture pull")
            return real_pull(self, *a, **kw)

        monkeypatch.setattr(TpuSpanStore, "_pull_evicted_rows", flaky)
        n = 2 * CFG.capacity // 2
        failed_batch = None
        for tid in range(1, n + 1):
            batch = make_trace(tid)
            try:
                tiered.apply(batch)
            except TimeoutError:
                failed_batch = batch  # aborted write: retry it
                tiered.apply(batch)
            oracle.apply(batch)
        assert failed_batch is not None, "the fault never fired"
        # Coverage stayed contiguous and answers exact.
        segs = tiered.archive.snapshot()
        assert segs and segs[0].gid_lo == 0
        for a, b in zip(segs, segs[1:]):
            assert a.gid_hi == b.gid_lo
        for tid in (1, 2, n):
            assert (tiered.get_spans_by_trace_ids([tid])
                    == oracle.get_spans_by_trace_ids([tid])), tid


class TestTieredConformance:
    """The acceptance drive (ISSUE 3): 4x ring capacity, answers match
    the memory-store oracle exactly, including evicted spans."""

    @pytest.fixture(scope="class")
    def stores(self):
        n_traces = 4 * CFG.capacity // 2  # 2 spans/trace -> 4x ring
        return build_tiered(n_traces)

    def test_ring_turned_over_and_segments_exist(self, stores):
        tiered, _ = stores
        counters = tiered.counters()
        assert counters["ring_laps"] >= 3
        assert counters["archive_segments_written"] >= 1
        assert counters["archive_compactions"] >= 1

    def test_trace_fetch_matches_oracle_incl_evicted(self, stores):
        tiered, oracle = stores
        n = 4 * CFG.capacity // 2
        sample = [1, 2, 3, n // 4, n // 2, n - 1, n]
        for tid in sample:
            assert (tiered.get_spans_by_trace_ids([tid])
                    == oracle.get_spans_by_trace_ids([tid])), tid
        # Batched form too, mixed found/missing.
        assert (tiered.get_spans_by_trace_ids(sample + [10 ** 12])
                == oracle.get_spans_by_trace_ids(sample + [10 ** 12]))

    def test_trace_ids_match_oracle_full_range(self, stores):
        tiered, oracle = stores
        end_ts = 1 << 60
        big = 10 * 4 * CFG.capacity
        for q in (
            ("web", "index"), ("web", None), ("api", "lookup"),
            ("db", None),
        ):
            got = tiered.get_trace_ids_by_name(q[0], q[1], end_ts, big)
            want = oracle.get_trace_ids_by_name(q[0], q[1], end_ts, big)
            assert got == want, q
        for q in (
            ("api", "boom", None), ("api", "k", b"v1"),
            ("api", "k", None),
        ):
            got = tiered.get_trace_ids_by_annotation(
                q[0], q[1], q[2], end_ts, big)
            want = oracle.get_trace_ids_by_annotation(
                q[0], q[1], q[2], end_ts, big)
            assert got == want, q

    def test_trace_ids_limit_union_is_exact(self, stores):
        """Small limits exercise the cross-tier top-k union proof."""
        tiered, oracle = stores
        end_ts = 1 << 60
        for limit in (1, 3, 10):
            got = tiered.get_trace_ids_by_name("web", None, end_ts,
                                               limit)
            want = oracle.get_trace_ids_by_name("web", None, end_ts,
                                                limit)
            assert got == want, limit

    def test_exist_and_durations_match_oracle(self, stores):
        tiered, oracle = stores
        n = 4 * CFG.capacity // 2
        qt = [1, 2, n // 2, n, 10 ** 12]
        assert tiered.traces_exist(qt) == oracle.traces_exist(qt)
        assert (tiered.get_traces_duration(qt)
                == oracle.get_traces_duration(qt))

    def test_dependencies_match_oracle(self, stores):
        from zipkin_tpu.aggregate.job import aggregate_spans

        tiered, oracle = stores
        want = {
            (l.parent, l.child): l.duration_moments.count
            for l in aggregate_spans(oracle.spans).links
        }
        got = {
            (l.parent, l.child): l.duration_moments.count
            for l in tiered.get_dependencies().links
        }
        assert got == want

    def test_duration_quantiles_match_oracle(self, stores):
        from zipkin_tpu.ops.quantile import quantiles_host

        tiered, oracle = stores
        gamma = (1.0 + CFG.quantile_alpha) / (1.0 - CFG.quantile_alpha)
        qs = [0.5, 0.95, 0.99]
        for svc in ("api", "db"):
            counts = np.zeros(CFG.quantile_buckets, np.int64)
            durs = [
                s.duration for s in oracle.spans
                if s.service_name == svc and s.duration is not None
            ]
            SK.hist_add(counts, np.asarray(durs, np.int64), gamma)
            want = quantiles_host(counts, gamma, 1.0, qs)
            got = tiered.service_duration_quantiles(svc, qs)
            assert got == want, svc

    def test_cold_sketches_answer_without_rows(self, stores):
        tiered, _ = stores
        cold_q = tiered.cold_duration_quantiles("api", [0.5, 0.99])
        assert cold_q is not None and all(v == v for v in cold_q)
        est = tiered.cold_estimated_unique_traces()
        cold_spans = tiered.counters()["archive_cold_spans"]
        assert 0.3 * cold_spans / 2 <= est  # 2 spans per trace

    def test_zone_map_prunes_narrow_time_range(self, stores):
        tiered, oracle = stores
        before = tiered.archive.c_pruned.value
        # The earliest traces' window: every later segment's minimum
        # last-ts exceeds this end_ts and must be skipped unread.
        got = tiered.get_trace_ids_by_name("web", None, 1_400, 50)
        want = oracle.get_trace_ids_by_name("web", None, 1_400, 50)
        assert got == want
        assert tiered.archive.c_pruned.value > before

    def test_counters_and_registry_metrics(self, stores):
        tiered, _ = stores
        c = tiered.counters()
        assert c["archive_segments_written"] >= 1
        assert c["archive_compactions"] >= 1
        assert c["archive_cold_spans"] > 0
        assert c["archive_captures"] >= 1
        assert c["archive_cold_bytes"] < c["archive_cold_raw_bytes"]


class TestTieredMisc:
    def test_multi_matches_singular(self):
        tiered, oracle = build_tiered(CFG.capacity)
        end_ts = 1 << 60
        queries = [
            ("name", "web", "index", end_ts, 20),
            ("name", "db", None, end_ts, 10),
            ("annotation", "api", "boom", None, end_ts, 20),
        ]
        got = tiered.get_trace_ids_multi(queries)
        assert got[0] == oracle.get_trace_ids_by_name(
            "web", "index", end_ts, 20)
        assert got[1] == oracle.get_trace_ids_by_name(
            "db", None, end_ts, 10)
        assert got[2] == oracle.get_trace_ids_by_annotation(
            "api", "boom", None, end_ts, 20)

    def test_service_and_span_name_catalogs(self):
        tiered, oracle = build_tiered(CFG.capacity)
        assert (tiered.get_all_service_names()
                == oracle.get_all_service_names())
        for svc in ("web", "api", "db"):
            assert (tiered.get_span_names(svc)
                    == oracle.get_span_names(svc)), svc

    def test_pin_through_tiers_banks_cold_rows(self):
        tiered, oracle = build_tiered(2 * CFG.capacity)
        # Trace 1 is long evicted from the ring; pinning must bank its
        # cold rows (the pre-cold-tier pin path could only bank what
        # the ring still held).
        tiered.set_time_to_live(1, 3600.0)
        assert tiered.hot.pins.get(1)
        assert (tiered.get_spans_by_trace_ids([1])
                == oracle.get_spans_by_trace_ids([1]))

    def test_capture_now_flushes_resident_window(self):
        tiered, oracle = build_tiered(CFG.capacity // 4)  # no wrap yet
        assert len(tiered.archive) == 0
        tiered.capture_now()
        assert len(tiered.archive) >= 1
        segs = tiered.archive.snapshot()
        assert segs[-1].gid_hi == tiered.hot._wp
        # Overlapping tiers still answer exactly (gid dedupe).
        assert (tiered.get_spans_by_trace_ids([1])
                == oracle.get_spans_by_trace_ids([1]))


def test_tiered_checkpoint_roundtrip(tmp_path):
    from zipkin_tpu import checkpoint

    tiered, oracle = build_tiered(3 * CFG.capacity // 2)
    path = str(tmp_path / "ckpt")
    checkpoint.save(tiered, path)
    restored = checkpoint.load(path)
    assert isinstance(restored, TieredSpanStore)
    n = 3 * CFG.capacity // 2
    for tid in (1, n // 2, n):
        assert (restored.get_spans_by_trace_ids([tid])
                == oracle.get_spans_by_trace_ids([tid])), tid
    end_ts = 1 << 60
    assert (restored.get_trace_ids_by_name("web", None, end_ts, 10 * n)
            == oracle.get_trace_ids_by_name("web", None, end_ts,
                                            10 * n))
    # Post-restore ingest keeps capturing.
    extra = make_trace(10 ** 6)
    restored.apply(extra)
    oracle.apply(extra)
    assert (restored.get_spans_by_trace_ids([10 ** 6])
            == oracle.get_spans_by_trace_ids([10 ** 6]))


@pytest.mark.parametrize("name", conformance_test_names())
def test_tiered_store_conformance(name):
    """The SpanStoreValidator suite straight over the tiered store —
    the federation is a SpanStore like any other backend."""
    def factory():
        return TieredSpanStore(TpuSpanStore(CFG), params=PARAMS)

    run_conformance_test(name, factory)
