"""Native C++ span parser: parity with the pure-python codec paths.

Skipped wholesale when g++ is unavailable (the python paths remain the
functional fallback)."""

import numpy as np
import pytest

from zipkin_tpu.columnar.dictionary import DictionarySet
from zipkin_tpu.columnar.encode import SpanCodec
from zipkin_tpu.models.span import (
    Annotation,
    AnnotationType,
    BinaryAnnotation,
    Endpoint,
    Span,
)
from zipkin_tpu.wire.thrift import span_to_bytes

native = pytest.importorskip("zipkin_tpu.native")
if not native.available():
    pytest.skip("g++ unavailable; native codec not built",
                allow_module_level=True)

WEB = Endpoint(0x01010101, 80, "Web")
API = Endpoint(0x02020202, 443, "api")


def spans_fixture():
    return [
        Span(
            trace_id=-5, name="GET /x", id=7, parent_id=None,
            annotations=(
                Annotation(100, "cs", WEB),
                Annotation(110, "sr", API),
                Annotation(150, "custom-anno", API),
                Annotation(190, "ss", API),
                Annotation(200, "cr", WEB),
            ),
            binary_annotations=(
                BinaryAnnotation("http.uri", "/x", AnnotationType.STRING, API),
                BinaryAnnotation("raw", b"\x01\x02", AnnotationType.BYTES, None),
                BinaryAnnotation("n", 17, AnnotationType.I32, None),
            ),
            debug=True,
        ),
        Span(trace_id=2**63 - 1, name="", id=-1, parent_id=7,
             annotations=(Annotation(50, "sr", API),)),
        Span(trace_id=3, name="bare", id=4),
    ]


def payload_of(spans):
    return b"".join(span_to_bytes(s) for s in spans)


class TestNativeParser:
    def test_columns_match_python_codec(self):
        spans = spans_fixture()
        dicts = DictionarySet()
        py = SpanCodec(dicts).encode(spans)
        nat, name_lc = native.parse_spans_columnar(payload_of(spans), dicts)
        for col in py.SPAN_COLUMNS + py.ANN_COLUMNS + py.BANN_COLUMNS:
            np.testing.assert_array_equal(
                getattr(nat, col), getattr(py, col), err_msg=col
            )

    def test_decodes_back_to_spans(self):
        spans = spans_fixture()
        dicts = DictionarySet()
        codec = SpanCodec(dicts)
        nat, _ = native.parse_spans_columnar(payload_of(spans), dicts)
        assert codec.decode(nat) == spans

    def test_name_lc_column(self):
        spans = [Span(trace_id=1, name="GET", id=1),
                 Span(trace_id=1, name="", id=2)]
        dicts = DictionarySet()
        nat, name_lc = native.parse_spans_columnar(payload_of(spans), dicts)
        assert dicts.span_names.decode(int(name_lc[0])) == "get"
        assert name_lc[1] == -1

    def test_malformed_raises(self):
        with pytest.raises(ValueError):
            native.parse_spans_columnar(b"\xff\xff\xff", DictionarySet())

    def test_base64(self):
        import base64

        raw = bytes(range(256))
        assert native.base64_decode(base64.b64encode(raw)) == raw
        with pytest.raises(ValueError):
            native.base64_decode(b"!!!!")

    def test_indexable_excludes_client_service(self):
        cl = Endpoint(1, 1, "client")
        spans = [
            Span(trace_id=1, name="a", id=1,
                 annotations=(Annotation(5, "cs", cl),)),
            Span(trace_id=2, name="b", id=2,
                 annotations=(Annotation(5, "sr", API),)),
        ]
        dicts = DictionarySet()
        batch, _ = native.parse_spans_columnar(payload_of(spans), dicts)
        idx = native.indexable_from_batch(batch, dicts)
        np.testing.assert_array_equal(idx, [False, True])

    def test_write_thrift_into_tpu_store(self):
        from zipkin_tpu.store.device import StoreConfig
        from zipkin_tpu.store.tpu import TpuSpanStore

        cfg = StoreConfig(
            capacity=1 << 9, ann_capacity=1 << 11, bann_capacity=1 << 10,
            max_services=16, max_span_names=64, max_annotation_values=64,
            max_binary_keys=16, cms_width=1 << 9, hll_p=6,
            quantile_buckets=128,
        )
        store = TpuSpanStore(cfg)
        spans = spans_fixture()
        n = store.write_thrift(payload_of(spans))
        assert n == 3
        got = store.get_spans_by_trace_ids([-5])
        assert got and got[0] == [spans[0]]
        assert store.get_all_service_names() == {"web", "api"}
