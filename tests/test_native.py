"""Native C++ span parser: parity with the pure-python codec paths.

Skipped wholesale when g++ is unavailable (the python paths remain the
functional fallback)."""

import numpy as np
import pytest

from zipkin_tpu.columnar.dictionary import DictionarySet
from zipkin_tpu.columnar.encode import SpanCodec
from zipkin_tpu.models.span import (
    Annotation,
    AnnotationType,
    BinaryAnnotation,
    Endpoint,
    Span,
)
from zipkin_tpu.wire.thrift import span_to_bytes

native = pytest.importorskip("zipkin_tpu.native")
if not native.available():
    pytest.skip("g++ unavailable; native codec not built",
                allow_module_level=True)

WEB = Endpoint(0x01010101, 80, "Web")
API = Endpoint(0x02020202, 443, "api")


def spans_fixture():
    return [
        Span(
            trace_id=-5, name="GET /x", id=7, parent_id=None,
            annotations=(
                Annotation(100, "cs", WEB),
                Annotation(110, "sr", API),
                Annotation(150, "custom-anno", API),
                Annotation(190, "ss", API),
                Annotation(200, "cr", WEB),
            ),
            binary_annotations=(
                BinaryAnnotation("http.uri", "/x", AnnotationType.STRING, API),
                BinaryAnnotation("raw", b"\x01\x02", AnnotationType.BYTES, None),
                BinaryAnnotation("n", 17, AnnotationType.I32, None),
            ),
            debug=True,
        ),
        Span(trace_id=2**63 - 1, name="", id=-1, parent_id=7,
             annotations=(Annotation(50, "sr", API),)),
        Span(trace_id=3, name="bare", id=4),
    ]


def payload_of(spans):
    return b"".join(span_to_bytes(s) for s in spans)


class TestNativeParser:
    def test_columns_match_python_codec(self):
        spans = spans_fixture()
        dicts = DictionarySet()
        py = SpanCodec(dicts).encode(spans)
        nat, name_lc = native.parse_spans_columnar(payload_of(spans), dicts)
        for col in py.SPAN_COLUMNS + py.ANN_COLUMNS + py.BANN_COLUMNS:
            np.testing.assert_array_equal(
                getattr(nat, col), getattr(py, col), err_msg=col
            )

    def test_decodes_back_to_spans(self):
        spans = spans_fixture()
        dicts = DictionarySet()
        codec = SpanCodec(dicts)
        nat, _ = native.parse_spans_columnar(payload_of(spans), dicts)
        assert codec.decode(nat) == spans

    def test_name_lc_column(self):
        spans = [Span(trace_id=1, name="GET", id=1),
                 Span(trace_id=1, name="", id=2)]
        dicts = DictionarySet()
        nat, name_lc = native.parse_spans_columnar(payload_of(spans), dicts)
        assert dicts.span_names.decode(int(name_lc[0])) == "get"
        assert name_lc[1] == -1

    def test_malformed_raises(self):
        with pytest.raises(ValueError):
            native.parse_spans_columnar(b"\xff\xff\xff", DictionarySet())

    def test_base64(self):
        import base64

        raw = bytes(range(256))
        assert native.base64_decode(base64.b64encode(raw)) == raw
        with pytest.raises(ValueError):
            native.base64_decode(b"!!!!")

    def test_indexable_excludes_client_service(self):
        cl = Endpoint(1, 1, "client")
        spans = [
            Span(trace_id=1, name="a", id=1,
                 annotations=(Annotation(5, "cs", cl),)),
            Span(trace_id=2, name="b", id=2,
                 annotations=(Annotation(5, "sr", API),)),
        ]
        dicts = DictionarySet()
        batch, _ = native.parse_spans_columnar(payload_of(spans), dicts)
        idx = native.indexable_from_batch(batch, dicts)
        np.testing.assert_array_equal(idx, [False, True])

    def test_write_thrift_into_tpu_store(self):
        from zipkin_tpu.store.device import StoreConfig
        from zipkin_tpu.store.tpu import TpuSpanStore

        cfg = StoreConfig(
            capacity=1 << 9, ann_capacity=1 << 11, bann_capacity=1 << 10,
            max_services=16, max_span_names=64, max_annotation_values=64,
            max_binary_keys=16, cms_width=1 << 9, hll_p=6,
            quantile_buckets=128,
        )
        store = TpuSpanStore(cfg)
        spans = spans_fixture()
        n, dropped, n_debug = store.write_thrift(payload_of(spans))
        assert (n, dropped, n_debug) == (3, 0, 1)
        got = store.get_spans_by_trace_ids([-5])
        assert got and got[0] == [spans[0]]
        assert store.get_all_service_names() == {"web", "api"}


class TestFastIngestPath:
    """Scribe base64 → collector fast path → native parse → device →
    query-back, with sampling applied on the columnar batch
    (VERDICT r1 #4: the fast path must be the production decode path
    and must not bypass the sampler)."""

    def _store(self):
        from zipkin_tpu.store.device import StoreConfig
        from zipkin_tpu.store.tpu import TpuSpanStore

        return TpuSpanStore(StoreConfig(
            capacity=1 << 9, ann_capacity=1 << 11, bann_capacity=1 << 10,
            max_services=16, max_span_names=64, max_annotation_values=64,
            max_binary_keys=16, cms_width=1 << 9, hll_p=6,
            quantile_buckets=128,
        ))

    def test_scribe_to_device_query_back(self):
        import base64

        from zipkin_tpu.ingest.collector import Collector
        from zipkin_tpu.ingest.receiver import ResultCode, ScribeReceiver

        store = self._store()
        collector = Collector(store, max_queue=50, concurrency=2)
        rx = ScribeReceiver(collector.accept,
                            process_thrift=collector.accept_thrift)
        spans = spans_fixture()
        entries = [("zipkin", base64.b64encode(span_to_bytes(s)).decode())
                   for s in spans]
        entries.append(("other-category", "aWdub3JlZA=="))
        assert rx.log(entries) == ResultCode.OK
        collector.flush()
        assert rx.stats["ignored"] == 1
        assert collector.spans_stored == 3
        got = store.get_spans_by_trace_ids([-5])
        assert got and got[0] == [spans[0]]
        assert store.get_all_service_names() == {"web", "api"}

    def test_fast_path_applies_sampler(self):
        from zipkin_tpu.ingest.collector import Collector
        from zipkin_tpu.models.span import Span
        from zipkin_tpu.sampler.core import Sampler

        store = self._store()
        # rate 0 → threshold == Long.MaxValue: only debug spans survive.
        collector = Collector(store, sampler=Sampler(0.0),
                              max_queue=50, concurrency=1)
        spans = [
            Span(trace_id=11, name="drop-me", id=1,
                 annotations=(Annotation(5, "sr", API),)),
            Span(trace_id=12, name="keep-me", id=2, debug=True,
                 annotations=(Annotation(6, "sr", API),)),
        ]
        collector.accept_thrift(payload_of(spans))
        collector.flush()
        assert collector.spans_stored == 1
        assert collector.spans_dropped == 1
        assert store.get_spans_by_trace_ids([11]) == []
        kept = store.get_spans_by_trace_ids([12])
        assert kept and kept[0][0].name == "keep-me"

    def test_bad_payload_counted_not_fatal(self):
        from zipkin_tpu.ingest.collector import Collector

        store = self._store()
        collector = Collector(store, max_queue=50, concurrency=1)
        collector.accept_thrift(b"\xff\xfegarbage")
        collector.flush()
        assert collector.bad_payloads == 1
        assert collector.spans_stored == 0

    def test_corrupt_segment_does_not_poison_batch(self):
        """One corrupt scribe entry must cost only itself; the other
        segments' spans still land (slow-path per-entry semantics)."""
        from zipkin_tpu.ingest.collector import Collector

        store = self._store()
        collector = Collector(store, max_queue=50, concurrency=1)
        good = spans_fixture()
        segments = [span_to_bytes(s) for s in good]
        segments.insert(1, b"\xff\xfecorrupt")
        collector.accept_thrift(segments)
        collector.flush()
        assert collector.bad_payloads == 1
        assert collector.spans_stored == 3
        assert store.get_spans_by_trace_ids([-5])

    def test_sampling_does_not_pollute_dictionaries(self):
        """Sampled-out spans must not intern their service/span names
        (the slow path filters before the store ever sees them)."""
        from zipkin_tpu.ingest.collector import Collector
        from zipkin_tpu.models.span import Span
        from zipkin_tpu.sampler.core import Sampler

        store = self._store()
        collector = Collector(store, sampler=Sampler(0.0),
                              max_queue=50, concurrency=1)
        ghost = Endpoint(9, 9, "ghost-service")
        spans = [Span(trace_id=21, name="ghost-op", id=1,
                      annotations=(Annotation(5, "sr", ghost),))]
        collector.accept_thrift(payload_of(spans))
        collector.flush()
        assert collector.spans_dropped == 1
        assert store.dicts.services.get("ghost-service") is None
        assert store.dicts.span_names.get("ghost-op") is None

    def test_debug_spans_skip_sampler_counters(self):
        from zipkin_tpu.ingest.collector import Collector
        from zipkin_tpu.models.span import Span
        from zipkin_tpu.sampler.core import Sampler

        store = self._store()
        sampler = Sampler(0.0)
        collector = Collector(store, sampler=sampler,
                              max_queue=50, concurrency=1)
        spans = [Span(trace_id=31, name="d", id=1, debug=True,
                      annotations=(Annotation(5, "sr", API),))]
        collector.accept_thrift(payload_of(spans))
        collector.flush()
        # Slow-path parity: debug short-circuits before the sampler.
        assert sampler.allowed == 0 and sampler.denied == 0
        assert collector.spans_stored == 1
