"""graftlint (zipkin_tpu/analysis): per-rule fixture-corpus pins and
the tier-1 no-new-violations gate.

Every rule has a true-positive snippet it MUST flag and a
false-positive twin it MUST stay silent on (tests/graftlint_corpus/) —
pinning both sensitivity and specificity. The repo gate then runs the
full analyzer over zipkin_tpu/ against the checked-in baseline
(graftlint-baseline.json): any NEW finding fails tier 1, which is the
whole point — the lock/jit conventions PRs 4-8 hand-enforced are now
machine-checked before the concurrency surface grows again.
"""

import json
import os
import subprocess
import sys

import pytest

from zipkin_tpu.analysis import ALL_RULES, analyze, load_project
from zipkin_tpu.analysis import baseline as baseline_mod
from zipkin_tpu.analysis.rules_guard import suggest_annotations
from zipkin_tpu.analysis.rules_locks import build_edges

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = os.path.join(REPO, "tests", "graftlint_corpus")
BASELINE = os.path.join(REPO, "graftlint-baseline.json")


def _corpus_findings(fname):
    path = os.path.join(CORPUS, fname)
    assert os.path.exists(path), f"missing corpus fixture {fname}"
    return analyze(load_project([path], CORPUS))


@pytest.mark.parametrize("rule", ALL_RULES)
def test_rule_fires_on_true_positive(rule):
    fname = rule.replace("-", "_") + "_tp.py"
    found = {f.rule for f in _corpus_findings(fname)}
    assert rule in found, (
        f"{rule} went blind: {fname} no longer trips it")


@pytest.mark.parametrize("rule", ALL_RULES)
def test_rule_silent_on_false_positive(rule):
    fname = rule.replace("-", "_") + "_fp.py"
    got = [f for f in _corpus_findings(fname) if f.rule == rule]
    assert got == [], (
        f"{rule} cries wolf on its false-positive twin: "
        + "; ".join(f.render() for f in got))


def test_corpus_is_complete():
    """Every rule has both fixture files (a new rule must ship its
    corpus pair)."""
    for rule in ALL_RULES:
        stem = rule.replace("-", "_")
        for suffix in ("_tp.py", "_fp.py"):
            assert os.path.exists(os.path.join(CORPUS, stem + suffix))


# -- the tier-1 gate -------------------------------------------------------


def _repo_project():
    return load_project([os.path.join(REPO, "zipkin_tpu")], REPO)


def test_repo_has_no_new_violations():
    """THE gate: the package analyzed against the checked-in baseline
    must produce zero new findings. Fix the code, suppress with a
    reasoned comment, or (last resort) regenerate the baseline via
    scripts/lint.py --write-baseline and justify the diff."""
    findings = analyze(_repo_project())
    if os.path.exists(BASELINE):
        new, _stale = baseline_mod.diff(
            findings, baseline_mod.load(BASELINE))
    else:
        new = findings
    assert new == [], (
        "new graftlint findings:\n"
        + "\n".join(f.render() for f in new))


def test_lock_graph_sees_the_real_architecture():
    """The acquisition graph must contain the canonical write-path
    edges — if the analyzer stops resolving them, the order/cycle
    rules silently stop protecting anything."""
    project = _repo_project()
    edges = {(a, b) for a, b, *_ in build_edges(project)}
    expected = {
        # encode -> capture -> commit -> mirror, the r9-r11 spine
        ("TpuSpanStore._lock", "TpuSpanStore._cap_lock"),
        ("TpuSpanStore._cap_lock", "TpuSpanStore._rw"),
        ("TpuSpanStore._rw", "SketchMirror._lock"),
        # stage-1 journaling under the encode lock (r10)
        ("TpuSpanStore._lock", "WriteAheadLog._cond"),
        # capture hand-off to the background sealer (r9)
        ("TpuSpanStore._cap_lock", "_StageBase._cond"),
        # sharded kernel cache built under the read lock (this PR)
        ("ShardedSpanStore._rw", "ShardedSpanStore._kernels_lock"),
    }
    missing = expected - edges
    assert not missing, f"lock graph lost edges: {sorted(missing)}"
    # And every declared lock is rank-annotated (the unannotated-lock
    # rule keeps this true; assert directly so the invariant survives
    # rule-list edits).
    unranked = [k for k, d in project.locks.items() if d.rank is None]
    assert unranked == [], unranked


def test_analyzer_runtime_budget():
    """The tier-1 lane budgets <= 30s for the analyzer; the full
    package parse + rules must stay an order of magnitude under."""
    import time

    t0 = time.perf_counter()
    analyze(_repo_project())
    assert time.perf_counter() - t0 < 30.0


# -- baseline workflow -----------------------------------------------------


def test_baseline_roundtrip_and_diff(tmp_path):
    findings = _corpus_findings("guarded_by_tp.py")
    assert findings
    path = tmp_path / "base.json"
    baseline_mod.save(str(path), findings)
    new, stale = baseline_mod.diff(findings, baseline_mod.load(str(path)))
    assert new == [] and stale == []
    # One accepted instance does not cover a second occurrence.
    new, _ = baseline_mod.diff(findings + [findings[0]],
                               baseline_mod.load(str(path)))
    assert len(new) == 1
    # Fixing a finding leaves a stale entry (reported, not fatal).
    _, stale = baseline_mod.diff(findings[1:], baseline_mod.load(str(path)))
    assert len(stale) == 1


def test_cli_gates_against_baseline(tmp_path):
    """scripts/lint.py exit codes: 1 on new findings, 0 once they are
    baselined (the --baseline workflow end-to-end)."""
    tp = os.path.join(CORPUS, "swallowed_exception_tp.py")
    base = str(tmp_path / "b.json")
    env = dict(os.environ, PYTHONPATH=REPO)

    def run(*args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
             tp, "--repo-root", CORPUS, "--baseline", base, *args],
            capture_output=True, text=True, env=env, timeout=120)

    dirty = run("--format", "json")
    assert dirty.returncode == 1, dirty.stderr[-1500:]
    rec = json.loads(dirty.stdout.strip().splitlines()[-1])
    assert rec["findings_new"] >= 1
    wrote = run("--write-baseline")
    assert wrote.returncode == 0, wrote.stderr[-1500:]
    clean = run("--format", "json")
    assert clean.returncode == 0, clean.stderr[-1500:]
    rec = json.loads(clean.stdout.strip().splitlines()[-1])
    assert rec["findings_new"] == 0 and rec["findings_total"] >= 1


def test_fix_annotations_inserts_guarded_by(tmp_path):
    """--fix-annotations: an attribute consistently accessed under one
    lock gets the annotation written onto its __init__ assignment."""
    src = (
        "import threading\n"
        "\n\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()  # lock-order: 10 s\n"
        "        self._n = 0\n"
        "\n"
        "    def a(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
        "\n"
        "    def b(self):\n"
        "        with self._lock:\n"
        "            return self._n\n"
    )
    f = tmp_path / "mod.py"
    f.write_text(src)
    project = load_project([str(f)], str(tmp_path))
    props = suggest_annotations(project)
    assert [(p[2], p[3]) for p in props] == [("_n", "_lock")]
    from zipkin_tpu.analysis.rules_guard import apply_annotations

    edits = apply_annotations(str(tmp_path), props)
    assert len(edits) == 1
    assert "self._n = 0  # guarded-by: _lock" in f.read_text()
    # Idempotent: a second pass proposes nothing new.
    project = load_project([str(f)], str(tmp_path))
    assert suggest_annotations(project) == []


def test_mixed_attr_not_annotated(tmp_path):
    """--fix-annotations must NOT annotate an attr with any unlocked
    access or two candidate locks (ambiguous ownership is a human
    call)."""
    src = (
        "import threading\n"
        "\n\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()  # lock-order: 10 s\n"
        "        self._n = 0\n"
        "\n"
        "    def a(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
        "\n"
        "    def b(self):\n"
        "        return self._n\n"
    )
    f = tmp_path / "mod.py"
    f.write_text(src)
    project = load_project([str(f)], str(tmp_path))
    assert suggest_annotations(project) == []
