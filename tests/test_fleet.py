"""Fleet observability (obs/fleet): causal batch-lineage tracing
across the ship/apply boundary, metrics federation, the stall
watchdog + flight recorder, B3 child-join on the API surface, and the
live primary+follower trace-propagation acceptance gate."""

import os
import threading
import time

import pytest

from zipkin_tpu import obs
from zipkin_tpu.obs import fleet as fobs
from zipkin_tpu.obs.fleet import (
    FleetObs,
    FlightRecorder,
    FollowerLineage,
    LineageTracker,
    Watchdog,
    make_span,
    merge_sketches,
    registry_snapshot,
    render_federated,
    span_from_wire,
    span_to_wire,
)


def _drain_spans():
    """A sink that collects flushed span batches."""
    got = []

    def sink(spans):
        got.extend(spans)

    return got, sink


class TestWireCodec:
    def test_roundtrip(self):
        w = span_to_wire(7, 9, 3, "wal append", "zipkin-tpu",
                         1_000_000, 42, {"seq": "5"})
        s = span_from_wire(w)
        assert s.trace_id == 7 and s.id == 9 and s.parent_id == 3
        assert s.name == "wal append"
        assert s.annotations[0].host.service_name == "zipkin-tpu"
        assert s.annotations[1].timestamp - s.annotations[0].timestamp == 42
        assert dict((b.key, b.value) for b in s.binary_annotations) == {
            "seq": "5"}

    def test_root_parent_none(self):
        s = span_from_wire(span_to_wire(1, 2, None, "r", "svc", 10, 1))
        assert s.parent_id is None


class TestLineageTracker:
    def test_stamp_sampling_cadence(self):
        got, sink = _drain_spans()
        t = LineageTracker(sink, sample_every=4)
        extras = [t.stamp() for _ in range(8)]
        assert all("ts" in e for e in extras)
        sampled = [i for i, e in enumerate(extras) if "b3" in e]
        assert sampled == [0, 4]  # first unit always traced

    def test_unit_spans_causally_linked(self):
        got, sink = _drain_spans()
        t = LineageTracker(sink, sample_every=1)
        extra = t.stamp()
        t.note_append(3, extra)
        t.on_durable(3)
        t.note_shipped(3, "r1")
        t.flush()
        by_name = {s.name: s for s in got}
        assert set(by_name) == {"ingest unit", "wal append", "wal fsync",
                                "ship"}
        root = by_name["ingest unit"]
        tid, sid = extra["b3"]
        assert root.trace_id == tid and root.id == sid
        assert root.parent_id is None
        for name in ("wal append", "wal fsync", "ship"):
            child = by_name[name]
            assert child.trace_id == tid
            assert child.parent_id == sid
            assert child.id != sid

    def test_remote_spans_join_same_trace(self):
        got, sink = _drain_spans()
        t = LineageTracker(sink, sample_every=1)
        extra = t.stamp()
        t.note_append(1, extra)
        tid, sid = extra["b3"]
        t.ingest_remote_spans("r1", [
            span_to_wire(tid, 12345, sid, "replica apply",
                         "zipkin-tpu-r1", 50, 7),
            {"broken": True},  # malformed entries drop, not raise
        ])
        t.flush()
        applied = [s for s in got if s.name == "replica apply"]
        assert len(applied) == 1
        assert applied[0].trace_id == tid and applied[0].parent_id == sid

    def test_suppressed_blocks_reentrant_flush(self):
        flushed = []

        def sink(spans):
            flushed.append(list(spans))

        t = LineageTracker(sink, sample_every=1)
        for seq in range(t.FLUSH_AT + 1):
            t.note_append(seq, t.stamp())
        with t.suppressed():
            t.flush()
            assert not flushed  # suppressed: nothing may emit
        t.flush()
        assert flushed and not t._buf

    def test_sink_failure_counts_drops_not_raises(self):
        reg = obs.Registry()

        def bad_sink(spans):
            raise RuntimeError("store down")

        t = LineageTracker(bad_sink, registry=reg, sample_every=1)
        t.note_append(1, t.stamp())
        t.flush()  # must not raise
        assert reg.get("zipkin_lineage_spans_dropped_total").value > 0

    def test_stage_sketch_observes(self):
        reg = obs.Registry()
        got, sink = _drain_spans()
        t = LineageTracker(sink, registry=reg, sample_every=1)
        t.note_append(1, t.stamp())
        t.on_durable(1)
        sk = reg.get("zipkin_lineage_stage_seconds")
        stages = {labels[0][1]
                  for _suffix, labels, _v in sk.samples()
                  if labels and labels[0][0] == "stage"}
        assert {"append", "fsync"} <= stages

    def test_pending_bounded(self):
        got, sink = _drain_spans()
        t = LineageTracker(sink, sample_every=1)
        for seq in range(t.MAX_PENDING + 64):
            t.note_append(seq, t.stamp())
        assert len(t._pending) <= t.MAX_PENDING


class TestFollowerLineage:
    def _record(self, tracker):
        """One stamped WAL-style payload via the real encoder (an
        empty launch group still carries the full json header)."""
        from zipkin_tpu.wal.record import encode_unit

        extra = tracker.stamp()
        return encode_unit([], [], {}, extra=extra), extra

    def test_lag_and_apply_span_backhaul(self):
        got, sink = _drain_spans()
        t = LineageTracker(sink, sample_every=1)
        payload, extra = self._record(t)
        f = FollowerLineage("r1", mode="replica")
        f.observe_record(9, payload, apply_s=0.002)
        lag = f.lag_seconds()
        assert lag is not None and 0 <= lag < 60
        spans = f.take_spans()
        assert len(spans) == 1
        w = spans[0]
        tid, sid = extra["b3"]
        assert w["traceId"] == tid and w["parentId"] == sid
        assert w["name"] == "replica apply"
        assert w["service"] == "zipkin-tpu-r1"
        assert f.take_spans() == []  # drained

    def test_unstamped_record_harmless(self):
        from zipkin_tpu.wal.record import encode_unit

        f = FollowerLineage("r1")
        f.observe_record(1, encode_unit([], [], {}), apply_s=0.001)
        assert f.lag_seconds() is None
        assert f.take_spans() == []

    def test_backlog_bounded(self):
        got, sink = _drain_spans()
        t = LineageTracker(sink, sample_every=1)
        f = FollowerLineage("r1")
        for seq in range(f.MAX_BACKLOG + 32):
            payload, _ = self._record(t)
            f.observe_record(seq, payload, apply_s=0.001)
        assert len(f.take_spans()) <= f.MAX_BACKLOG

    def test_metrics_snapshot_throttled(self):
        reg = obs.Registry()
        reg.register(obs.Counter("x_total", "h")).inc()
        now = [1000.0]
        f = FollowerLineage("r1", registry=reg, clock=lambda: now[0])
        snap = f.maybe_metrics_snapshot()
        assert snap is not None and snap["v"] == 1
        assert f.maybe_metrics_snapshot() is None  # within interval
        now[0] += f.METRICS_PUSH_INTERVAL_S + 0.1
        assert f.maybe_metrics_snapshot() is not None

    def test_lag_gauge_registered(self):
        reg = obs.Registry()
        f = FollowerLineage("r1", registry=reg)
        assert reg.get("zipkin_replication_lag_seconds").value == 0.0
        got, sink = _drain_spans()
        t = LineageTracker(sink, sample_every=1)
        payload, _ = self._record(t)
        f.observe_record(1, payload, apply_s=0.001)
        assert reg.get("zipkin_replication_lag_seconds").value >= 0.0


class TestFederation:
    def _registry(self, counter=3.0, sketch_vals=(0.01, 0.02)):
        reg = obs.Registry()
        reg.register(obs.Counter("f_req_total", "requests")).inc(counter)
        sk = reg.register(obs.LatencySketch("f_lat_seconds", "latency"))
        for v in sketch_vals:
            sk.observe(v)
        return reg

    def test_single_source_bitwise_vs_own_scrape(self):
        """A federated render of one process's snapshot differs from
        its own scrape ONLY by the injected labels — every value
        formats identically (same _fmt path)."""
        reg = self._registry()
        own = reg.render_text()
        fed = render_federated(
            [((("role", "primary"),), registry_snapshot(reg))])
        own_vals = sorted(line.rsplit(" ", 1)[1]
                          for line in own.splitlines()
                          if line and not line.startswith("#"))
        fed_vals = sorted(line.rsplit(" ", 1)[1]
                          for line in fed.splitlines()
                          if line and not line.startswith("#"))
        assert own_vals == fed_vals

    def test_merged_scrape_no_double_counting(self):
        a = self._registry(counter=3.0)
        b = self._registry(counter=5.0)
        fed = render_federated([
            ((("role", "primary"),), registry_snapshot(a)),
            ((("role", "follower"), ("follower", "r1")),
             registry_snapshot(b)),
        ])
        rows = [l for l in fed.splitlines()
                if l.startswith("f_req_total")]
        assert len(rows) == 2
        assert any('role="primary"' in r and r.endswith(" 3")
                   for r in rows)
        assert any('follower="r1"' in r and r.endswith(" 5")
                   for r in rows)

    def test_sketch_monoid_merge(self):
        import numpy as np

        a = obs.LatencySketch("m_seconds", "h")
        b = obs.LatencySketch("m_seconds", "h")
        both = obs.LatencySketch("m_seconds", "h")
        for v in (0.001, 0.01, 0.1):
            a.observe(v)
            both.observe(v)
        for v in (0.2, 0.4):
            b.observe(v)
            both.observe(v)
        merged = merge_sketches("m_seconds", "h", [
            fobs._sketch_state(a), fobs._sketch_state(b)])
        assert np.array_equal(merged.counts, both.counts)
        assert merged.moments.n == both.moments.n
        assert list(merged.samples()) == list(both.samples())

    def test_fleet_status_rolls_up(self):
        reg_a = obs.Registry()
        sk = reg_a.register(obs.LatencySketch(
            "zipkin_replication_visible_lag_seconds", "lag"))
        sk.observe(0.01)
        reg_b = obs.Registry()
        sk2 = reg_b.register(obs.LatencySketch(
            "zipkin_replication_visible_lag_seconds", "lag"))
        sk2.observe(0.03)

        fleet = FleetObs(
            role="primary", registry=reg_a,
            remote_sources=lambda: [
                ((("role", "follower"), ("follower", "r1")),
                 registry_snapshot(reg_b))])
        st = fleet.status()
        assert len(st["processes"]) == 2
        merged = st["merged"]["zipkin_replication_visible_lag_seconds"]
        assert merged["count"] == 2


class TestFlightRecorder:
    def test_bounded_ring_keeps_newest(self):
        r = FlightRecorder(capacity=4)
        for i in range(10):
            r.record("k", severity="info", i=i)
        evs = r.events()
        assert len(evs) == 4
        assert [e["fields"]["i"] for e in evs] == [6, 7, 8, 9]
        assert [e["fields"]["i"] for e in r.events(limit=2)] == [8, 9]

    def test_event_shape(self):
        r = FlightRecorder()
        r.record("watchdog", severity="error", probe="fsync",
                 reason="parked")
        (e,) = r.events()
        assert e["kind"] == "watchdog" and e["severity"] == "error"
        assert e["fields"]["probe"] == "fsync"
        assert "tsUs" in e and "seq" in e


class TestWatchdog:
    def test_transitions_recorded_once(self):
        rec = FlightRecorder()
        reg = obs.Registry()
        wd = Watchdog(recorder=rec, registry=reg)
        state = {"ok": True}
        wd.add_probe("p", lambda: (state["ok"],
                                   None if state["ok"] else "stuck",
                                   1.0))
        assert wd.check()["ready"] is True
        state["ok"] = False
        h = wd.check()
        assert h["ready"] is False and h["live"] is True
        assert h["reasons"][0]["probe"] == "p"
        wd.check()  # still failing: no new transition event
        state["ok"] = True
        wd.check()
        kinds = [(e["kind"], e["fields"].get("probe"))
                 for e in rec.events()]
        assert kinds.count(("watchdog_trip", "p")) == 1
        assert kinds.count(("watchdog_clear", "p")) == 1
        assert reg.get("zipkin_watchdog_trips_total").value == 1
        assert reg.get("zipkin_watchdog_failing_probes").value == 0

    def test_probe_exception_is_a_failure(self):
        wd = Watchdog()

        def boom():
            raise RuntimeError("probe died")

        wd.add_probe("boom", boom)
        h = wd.check()
        assert h["ready"] is False
        assert "probe died" in h["reasons"][0]["reason"]

    def test_fsync_parked_probe(self, tmp_path):
        from zipkin_tpu.wal import WriteAheadLog

        wal = WriteAheadLog(str(tmp_path / "w"), fsync="off")
        try:
            probe = fobs.fsync_parked_probe(wal)
            assert probe()[0] is True
            wal._sync_error = RuntimeError("disk gone")
            ok, reason, _ = probe()
            assert ok is False and "disk gone" in reason
        finally:
            wal._sync_error = None
            wal.close()

    def test_follower_lag_probe_thresholds(self):
        st = {"lagRecords": 5, "lagSeconds": 1.0}
        probe = fobs.follower_lag_probe(lambda: st,
                                        max_lag_records=10,
                                        max_lag_seconds=30.0)
        assert probe()[0] is True
        st["lagRecords"] = 50
        assert probe()[0] is False
        st["lagRecords"] = 5
        st["lagSeconds"] = 31.0
        assert probe()[0] is False


class TestDispatcherSpanSink:
    def test_fused_batch_parents_under_request_context(self):
        from types import SimpleNamespace

        from zipkin_tpu.parallel.dispatch import CrossShardDispatcher

        store = SimpleNamespace(
            CAT_BUNDLE_KEYS=frozenset(),
            _cat_direct=lambda key: {"n": 1})
        reg = obs.Registry()
        d = CrossShardDispatcher(store, registry=reg)
        spans = []
        d.span_sink = SimpleNamespace(
            record_span=lambda *a, **k: spans.append((a, k)))
        token = fobs.set_request_context(0xAB, 0xCD)
        try:
            assert d.cat("svc") == {"n": 1}
        finally:
            fobs.reset_request_context(token)
        d.close()
        assert spans, "dispatch span not recorded"
        (args, _kw) = spans[0]
        trace_id, parent_id, name = args[0], args[1], args[2]
        assert (trace_id, parent_id) == (0xAB, 0xCD)
        assert name == "shard dispatch"

    def test_no_context_no_span(self):
        from types import SimpleNamespace

        from zipkin_tpu.parallel.dispatch import CrossShardDispatcher

        store = SimpleNamespace(CAT_BUNDLE_KEYS=frozenset(),
                                _cat_direct=lambda key: {})
        d = CrossShardDispatcher(store, registry=obs.Registry())
        spans = []
        d.span_sink = SimpleNamespace(
            record_span=lambda *a, **k: spans.append(a))
        d.cat("svc")
        d.close()
        assert not spans

    def test_queue_age_idle_zero(self):
        from types import SimpleNamespace

        from zipkin_tpu.parallel.dispatch import CrossShardDispatcher

        d = CrossShardDispatcher(
            SimpleNamespace(CAT_BUNDLE_KEYS=frozenset(),
                            _cat_direct=lambda key: {}),
            registry=obs.Registry())
        assert d.queue_age_s() == 0.0
        d.close()


class TestApiFleetSurface:
    def _api(self, fleet):
        from zipkin_tpu.api import ApiServer
        from zipkin_tpu.ingest.collector import Collector
        from zipkin_tpu.query.service import QueryService
        from zipkin_tpu.store.memory import InMemorySpanStore

        store = InMemorySpanStore()
        collector = Collector(store, concurrency=0, self_trace=False)
        api = ApiServer(QueryService(store), collector, fleet=fleet)
        return store, collector, api

    def test_health_flips_on_failing_probe(self):
        rec = FlightRecorder()
        wd = Watchdog(recorder=rec)
        state = {"ok": True}
        wd.add_probe("fsync", lambda: (
            state["ok"], None if state["ok"] else "wal fsync parked",
            None))
        fleet = FleetObs(role="primary", registry=obs.Registry(),
                         watchdog=wd, recorder=rec)
        _store, _collector, api = self._api(fleet)
        code, body = api.handle("GET", "/api/health", {}, headers={})
        assert code == 200 and body["ready"] is True
        state["ok"] = False
        code, body = api.handle("GET", "/api/health", {}, headers={})
        assert code == 503 and body["ready"] is False
        assert body["reasons"][0]["reason"] == "wal fsync parked"
        # The trip is visible in the flight recorder.
        code, body = api.handle("GET", "/debug/events", {}, headers={})
        assert code == 200
        assert any(e["kind"] == "watchdog_trip" for e in body["events"])

    def test_health_without_fleet_always_ready(self):
        _store, _collector, api = self._api(None)
        code, body = api.handle("GET", "/api/health", {}, headers={})
        assert code == 200 and body["ready"] is True

    def test_fleet_endpoint_and_merged_scrape(self):
        reg = obs.Registry()
        reg.register(obs.Counter("p_total", "h")).inc(2)
        freg = obs.Registry()
        freg.register(obs.Counter("p_total", "h")).inc(7)
        fleet = FleetObs(
            role="primary", registry=reg,
            remote_sources=lambda: [
                ((("role", "follower"), ("follower", "r1")),
                 registry_snapshot(freg))])
        _store, _collector, api = self._api(fleet)
        code, body = api.handle("GET", "/api/fleet", {}, headers={})
        assert code == 200 and body["role"] == "primary"
        assert len(body["processes"]) == 2
        code, raw = api.handle("GET", "/metrics", {"fleet": "1"},
                               headers={})
        text = raw.body.decode("utf-8")
        assert code == 200
        rows = [l for l in text.splitlines() if l.startswith("p_total")]
        assert any('role="primary"' in r and r.endswith(" 2")
                   for r in rows)
        assert any('follower="r1"' in r and r.endswith(" 7")
                   for r in rows)

    def test_plain_scrape_unchanged_by_fleet_param_absence(self):
        fleet = FleetObs(role="primary", registry=obs.Registry())
        _store, _collector, api = self._api(fleet)
        code, raw = api.handle("GET", "/metrics", {}, headers={})
        assert code == 200
        text = raw.body.decode("utf-8")
        # Plain scrape stays the per-process registry: no injected
        # federation labels anywhere.
        assert 'role="primary"' not in text


@pytest.mark.slow
class TestLiveFleetTrace:
    """The acceptance gate: a primary+follower pair under ingest
    produces ONE causally-linked trace spanning
    encode → WAL append → fsync → ship → follower apply, queryable
    from the primary's own store."""

    def test_ship_pair_single_trace(self, tmp_path):
        from zipkin_tpu.replicate import (
            Follower,
            ReplicaTarget,
            ShipClient,
            ShipServer,
            WalShipper,
        )
        from zipkin_tpu.store import device as dev
        from zipkin_tpu.store.replica import ReplicaSpanStore
        from zipkin_tpu.store.tpu import TpuSpanStore
        from zipkin_tpu.tracegen import generate_traces
        from zipkin_tpu.wal import WriteAheadLog

        cfg = dev.StoreConfig(
            capacity=1 << 9, ann_capacity=1 << 11,
            bann_capacity=1 << 10, max_services=32,
            max_span_names=256, max_annotation_values=256,
            max_binary_keys=64, cms_width=1 << 10, hll_p=8,
            quantile_buckets=512)
        reg = obs.Registry()
        primary = TpuSpanStore(cfg)
        wal = WriteAheadLog(str(tmp_path / "wal"), fsync="off")
        primary.attach_wal(wal)
        tracker = LineageTracker(primary.apply, registry=reg,
                                 sample_every=1)
        primary.attach_lineage(tracker)
        shipper = WalShipper(primary, registry=reg, tracker=tracker)
        server = ShipServer(shipper, host="127.0.0.1", port=0)
        server.serve_in_thread()
        port = server.server_address[1]

        freg = obs.Registry()
        replica = ReplicaSpanStore(cfg, background_compaction=False)
        flin = FollowerLineage("r1", mode="replica", registry=freg)
        client = ShipClient("127.0.0.1", port, follower="r1",
                            mode="replica")
        follower = Follower(ReplicaTarget(replica), client,
                            registry=freg, lineage=flin)
        try:
            spans = [s for t in generate_traces(
                n_traces=20, max_depth=3, n_services=4) for s in t][:100]
            primary.apply(spans)
            wal.sync()
            deadline = time.monotonic() + 30.0
            while (replica.applied_seq() < wal.last_seq
                   and time.monotonic() < deadline):
                follower.step()
            assert replica.applied_seq() >= wal.last_seq
            follower.step()  # backhauls the buffered apply spans
            tracker.flush()
            wal.sync()

            found = primary.get_trace_ids_by_name(
                "zipkin-tpu", None, 1 << 62, 50)
            assert found, "no lineage trace recorded"
            want = {"ingest unit", "wal append", "wal fsync", "ship",
                    "replica apply"}
            complete = None
            for itid in found:
                trace = primary.get_spans_by_trace_ids(
                    [itid.trace_id])[0]
                names = {s.name for s in trace}
                if want <= names:
                    complete = trace
                    break
            assert complete is not None, (
                "no trace spans the full pipeline")
            root = next(s for s in complete
                        if s.name == "ingest unit"
                        and s.parent_id is None)
            for s in complete:
                if s.name in want - {"ingest unit"}:
                    assert s.parent_id == root.id, s.name
                    assert s.trace_id == root.trace_id
            applied = next(s for s in complete
                           if s.name == "replica apply")
            assert (applied.annotations[0].host.service_name
                    == "zipkin-tpu-r1")
            # Satellite 2: visible-lag gauge is live on the follower.
            assert flin.lag_seconds() is not None
            assert (freg.get("zipkin_replication_lag_seconds").value
                    >= 0.0)
            # Federation: both processes in one merged scrape.
            fleet = FleetObs(role="primary", registry=reg,
                             tracker=tracker,
                             remote_sources=shipper.fleet_sources,
                             replication=shipper.status)
            text = fleet.federated_text()
            assert 'role="primary"' in text
            assert 'follower="r1"' in text
            st = fleet.status()
            assert len(st["processes"]) == 2
        finally:
            server.shutdown()
            server.server_close()
            client.close()
            replica.close()
            wal.close()
