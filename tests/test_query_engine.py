"""Resident query engine (query/engine.py): sketch-tier exactness vs
the device read path, frontier-keyed result-cache correctness across
ingest commits / ring eviction / pin mutations, staleness-freedom
under concurrent ingest + query threads, and the executor's place in
the ordered shutdown sequence.
"""

import threading

import pytest

from zipkin_tpu.ingest.collector import Collector
from zipkin_tpu.query.engine import QueryEngine
from zipkin_tpu.query.service import QueryService
from zipkin_tpu.store.device import StoreConfig
from zipkin_tpu.store.memory import InMemorySpanStore
from zipkin_tpu.store.tpu import TpuSpanStore
from zipkin_tpu.tracegen import generate_traces

CONFIG = dict(
    capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
    max_services=32, max_span_names=64, max_annotation_values=256,
    max_binary_keys=64, cms_width=1 << 10, hll_p=8,
    quantile_buckets=256,
)
SPANS = [s for t in generate_traces(n_traces=40, max_depth=4,
                                    n_services=6) for s in t]
END_TS = max(s.last_timestamp for s in SPANS if s.last_timestamp) + 1
QS = [0.5, 0.95, 0.99]


def _store(spans=SPANS, **kw):
    st = TpuSpanStore(StoreConfig(**{**CONFIG, **kw}))
    for i in range(0, len(spans), 64):
        st.apply(spans[i:i + 64])
    return st


def _ids(rows):
    return [[(i.trace_id, i.timestamp) for i in r] for r in rows]


def _assert_sketch_matches_device(engine, store):
    """Every sketch-tier answer must equal the device read path's."""
    assert engine.get_all_service_names() == store.get_all_service_names()
    for svc in sorted(store.get_all_service_names()):
        assert engine.get_span_names(svc) == store.get_span_names(svc)
        assert (engine.service_duration_quantiles(svc, QS)
                == store.service_duration_quantiles(svc, QS)), svc
        assert engine.top_annotations(svc) == store.top_annotations(svc)
        assert engine.top_binary_keys(svc) == store.top_binary_keys(svc)
    assert (engine.estimated_unique_traces()
            == store.estimated_unique_traces())
    assert engine.get_span_names("no-such-service") == set()
    assert engine.service_duration_quantiles("no-such-service", QS) is None
    assert engine.top_annotations("no-such-service") == []


def test_sketch_tier_matches_device_path_exactly():
    """Incremental mirror deltas: after a serial drive every sketch
    answer is bitwise the device's, with zero mirror resyncs."""
    store = _store()
    engine = QueryEngine(store, window_s=0.0)
    assert store.sketch_mirror.warm  # never went cold: pure deltas
    _assert_sketch_matches_device(engine, store)
    assert engine.c_sketch.value > 0


def test_sketch_tier_resync_after_state_adoption():
    """adopt_state marks the mirror cold; the first sketch read
    resyncs from the device in one fetch and answers exactly."""
    store = _store()
    store.adopt_state(store.state, spans_written=store._wp)
    assert not store.sketch_mirror.warm
    engine = QueryEngine(store, window_s=0.0)
    _assert_sketch_matches_device(engine, store)
    assert store.sketch_mirror.warm


def test_pipelined_ingest_keeps_mirror_exact():
    """Deltas ride IngestUnits through the pipeline's commit thread;
    after drain the mirror equals the device aggregates."""
    store = TpuSpanStore(StoreConfig(**CONFIG))
    with store.pipelined(4):
        for i in range(0, len(SPANS), 64):
            store.apply(SPANS[i:i + 64])
        store.drain_pipeline()
        engine = QueryEngine(store, window_s=0.0)
        _assert_sketch_matches_device(engine, store)


def test_result_cache_hits_are_bitwise_equal_and_frontier_keyed():
    store = _store()
    engine = QueryEngine(store, window_s=0.0)
    svcs = sorted(store.get_all_service_names())
    queries = [("name", s, None, END_TS, 10) for s in svcs]
    cold = _ids(engine.get_trace_ids_multi(queries))
    h0, m0 = engine.c_hits.value, engine.c_misses.value
    warm = _ids(engine.get_trace_ids_multi(queries))
    assert warm == cold  # bitwise-equal hit
    assert engine.c_hits.value - h0 == len(queries)
    assert engine.c_misses.value == m0
    # Row reads cache too, and copies protect the cached value.
    tids = [t for r in cold for t, _ in r][:4]
    spans1 = engine.get_spans_by_trace_ids(tids)
    spans2 = engine.get_spans_by_trace_ids(tids)
    assert spans1 == spans2
    spans2[0].clear()  # mutating the returned copy ...
    assert engine.get_spans_by_trace_ids(tids) == spans1  # ... is safe
    assert engine.traces_exist(tids) == store.traces_exist(tids)
    assert (engine.get_traces_duration(tids)
            == store.get_traces_duration(tids))


def test_result_cache_invalidates_on_ingest_commit():
    """A commit advances the frontier: the next read recomputes and
    matches a fresh store read (no stale entry can ever be served)."""
    store = _store()
    engine = QueryEngine(store, window_s=0.0)
    svcs = sorted(store.get_all_service_names())
    queries = [("name", s, None, 1 << 61, 50) for s in svcs]
    f0 = store.write_frontier()
    engine.get_trace_ids_multi(queries)  # fills at f0
    extra = [s for t in generate_traces(n_traces=10, max_depth=3,
                                        n_services=6) for s in t]
    store.apply(extra)
    assert store.write_frontier() != f0
    after = _ids(engine.get_trace_ids_multi(queries))
    assert after == _ids(store.get_trace_ids_multi(queries))
    # The new spans are actually visible through the engine.
    new_tid = extra[0].trace_id
    assert engine.traces_exist([new_tid]) == {new_tid}


def test_result_cache_invalidates_on_pin_and_ttl_mutation():
    """Pin/TTL changes alter read answers without a device commit —
    the read epoch component of the frontier covers them."""
    store = _store()
    engine = QueryEngine(store, window_s=0.0)
    tid = SPANS[0].trace_id
    before = engine.get_spans_by_trace_ids([tid])
    f0 = store.write_frontier()
    store.set_time_to_live(tid, 3600.0)  # pin
    assert store.write_frontier() != f0
    assert engine.get_spans_by_trace_ids([tid]) == \
        store.get_spans_by_trace_ids([tid])
    assert before  # the trace existed all along


def test_cache_and_executor_exact_through_eviction_capture():
    """Tiered store, 4×-ring drive with queries interleaved: engine
    answers (which cache across the laps) always match the memory
    oracle, including spans only the cold tier still holds."""
    from zipkin_tpu.store.archive import ArchiveParams, TieredSpanStore

    # test_archive.CFG geometry: the suite's jit cache is already
    # warm at these shapes.
    cfg = StoreConfig(
        capacity=1 << 8, ann_capacity=1 << 10, bann_capacity=1 << 9,
        max_services=16, max_span_names=64, max_annotation_values=128,
        max_binary_keys=32, cms_width=1 << 9, hll_p=6,
        quantile_buckets=256,
    )
    n = 4 * cfg.capacity
    spans = [s for t in generate_traces(n_traces=n // 4, max_depth=3,
                                        n_services=8) for s in t][:n]
    hot = TpuSpanStore(cfg)
    tiered = TieredSpanStore(hot, params=ArchiveParams.for_config(
        cfg, compact_fanin=2, small_span_limit=cfg.capacity,
        bloom_bits=1 << 12, cms_width=1 << 10, hll_p=6,
    ))
    oracle = InMemorySpanStore()
    engine = QueryEngine(tiered, window_s=0.0)
    svc0 = None
    for i in range(0, len(spans), 128):
        tiered.apply(spans[i:i + 128])
        oracle.apply(spans[i:i + 128])
        if svc0 is None:
            svc0 = sorted(oracle.get_all_service_names())[0]
        # Interleaved query: fills the cache at this frontier ...
        engine.get_trace_ids_by_name(svc0, None, 1 << 61, 8)
    # ... and the final answers (cache long invalidated by later
    # commits) match the oracle exactly, evicted spans included.
    tids = sorted({s.trace_id for s in spans})
    sample = tids[:3] + tids[len(tids) // 2:len(tids) // 2 + 3] + tids[-3:]
    for t in sample:
        assert (engine.get_spans_by_trace_ids([t])
                == oracle.get_spans_by_trace_ids([t])), t
        assert (engine.get_spans_by_trace_ids([t])
                == oracle.get_spans_by_trace_ids([t])), t  # cached hit
    assert (_ids(engine.get_trace_ids_multi(
        [("name", svc0, None, 1 << 61, 10 * n)]))
        == _ids([oracle.get_trace_ids_by_name(svc0, None, 1 << 61,
                                              10 * n)]))
    # Sketch federation: catalog includes cold-only services.
    assert (engine.get_all_service_names()
            == tiered.get_all_service_names()
            == oracle.get_all_service_names())
    tiered.close()


def test_staleness_freedom_under_concurrent_ingest_and_query():
    """Writers and engine readers race; reads never error, and once
    writes drain every answer equals a fresh store read AND the
    memory oracle."""
    store = _store(spans=SPANS[:64])
    oracle = InMemorySpanStore()
    oracle.apply(SPANS[:64])
    engine = QueryEngine(store, window_s=0.0)
    rest = SPANS[64:]
    errors = []
    stop = threading.Event()

    def write():
        try:
            for i in range(0, len(rest), 32):
                store.apply(rest[i:i + 32])
                oracle.apply(rest[i:i + 32])
        finally:
            stop.set()

    svc0 = sorted(store.get_all_service_names())[0]

    def read():
        try:
            while not stop.is_set():
                engine.get_trace_ids_multi(
                    [("name", svc0, None, END_TS, 10)])
                engine.get_all_service_names()
                engine.traces_exist([SPANS[0].trace_id])
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=write)] + [
        threading.Thread(target=read) for _ in range(3)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors
    engine.drain()
    _assert_sketch_matches_device(engine, store)
    svcs = sorted(oracle.get_all_service_names())
    assert engine.get_all_service_names() == set(svcs)
    queries = [("name", s, None, 1 << 61, 50) for s in svcs]
    assert (_ids(engine.get_trace_ids_multi(queries))
            == _ids(store.get_trace_ids_multi(queries))
            == _ids([oracle.get_trace_ids_by_name(s, None, 1 << 61, 50)
                     for s in svcs]))


def test_executor_joins_ordered_shutdown():
    """The engine registers on the store; Collector.flush drains the
    standing executor, Collector.close stops it before the store
    closes — and queries still answer inline afterwards."""
    store = TpuSpanStore(StoreConfig(**CONFIG))
    collector = Collector(store, self_trace=False, concurrency=2)
    service = QueryService(store, coalesce_window_s=0.0)
    engine = service.engine
    assert engine in store.query_engines()
    collector.accept(SPANS[:64])
    collector.flush()  # drain-queries → drain-pipeline → seal → fsync
    svc0 = sorted(store.get_all_service_names())[0]
    want = _ids(engine.get_trace_ids_multi(
        [("name", svc0, None, END_TS, 10)]))
    assert want and want[0]  # the flushed spans are queryable
    collector.close()
    assert engine.executor.closed
    assert not engine.executor._thread.is_alive()
    # Inline fallback: identical answers, no standing thread.
    got = _ids(engine.get_trace_ids_multi(
        [("name", svc0, None, END_TS, 10)]))
    assert got == want


def test_checkpoint_save_drains_executor(tmp_path):
    """checkpoint.save quiesces registered engines before the gather
    (no query launch in flight when the consistent cut is taken), and
    a restored store's mirror resyncs to exact sketch answers."""
    from zipkin_tpu import checkpoint

    store = _store()
    engine = QueryEngine(store, window_s=0.0)
    drained = []
    orig = engine.drain
    engine.drain = lambda: (drained.append(True), orig())[1]
    checkpoint.save(store, str(tmp_path / "ckpt"))
    assert drained
    restored = checkpoint.load(str(tmp_path / "ckpt"))
    assert not restored.sketch_mirror.warm
    engine2 = QueryEngine(restored, window_s=0.0)
    _assert_sketch_matches_device(engine2, restored)


def test_window_plumbs_end_to_end():
    """--query-window-ms → QueryService → engine → executor, plus the
    runtime /vars/queryWindowMs route."""
    from zipkin_tpu.api.server import ApiServer
    from zipkin_tpu.main.example import build_parser

    args = build_parser().parse_args(["--query-window-ms", "7"])
    assert args.query_window_ms == 7.0
    store = InMemorySpanStore()
    store.apply(SPANS[:16])
    service = QueryService(store, coalesce_window_s=7 / 1000.0)
    assert service.engine.window_s == pytest.approx(0.007)
    api = ApiServer(service, collector=None)
    code, body = api.handle("GET", "/vars/queryWindowMs", {})
    assert code == 200 and body["queryWindowMs"] == pytest.approx(7.0)
    code, body = api.handle("POST", "/vars/queryWindowMs", {}, b"3.5")
    assert code == 200 and body["queryWindowMs"] == pytest.approx(3.5)
    assert service.engine.window_s == pytest.approx(0.0035)


def test_engine_on_host_store_is_transparent():
    """Memory/sql backends: no mirror, no frontier — the engine is a
    pure facade with identical answers."""
    store = InMemorySpanStore()
    store.apply(SPANS)
    engine = QueryEngine(store, window_s=0.0)
    svcs = sorted(store.get_all_service_names())
    assert engine.get_all_service_names() == set(svcs)
    for s in svcs[:3]:
        assert engine.get_span_names(s) == store.get_span_names(s)
        assert (_ids(engine.get_trace_ids_multi(
            [("name", s, None, END_TS, 10)]))
            == _ids([store.get_trace_ids_by_name(s, None, END_TS, 10)]))
    tid = SPANS[0].trace_id
    assert (engine.get_spans_by_trace_ids([tid])
            == store.get_spans_by_trace_ids([tid]))
    # No frontier ⇒ nothing cached, ever.
    assert len(engine.cache) == 0
