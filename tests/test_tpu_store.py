"""TpuSpanStore: conformance suite + device-store analytics.

The same behavioral suite the in-memory store passes runs against the
device store (reference pattern: SpanStoreValidator reused across
backends, SpanStoreValidator.scala:27).
"""

import numpy as np
import pytest

from zipkin_tpu.models.span import Annotation, BinaryAnnotation, Endpoint, Span
from zipkin_tpu.store.device import StoreConfig
from zipkin_tpu.store.tpu import TpuSpanStore
from zipkin_tpu.testing.conformance import (
    conformance_test_names,
    run_conformance_test,
)

SMALL = StoreConfig(
    capacity=1 << 10,
    ann_capacity=1 << 12,
    bann_capacity=1 << 11,
    max_services=32,
    max_span_names=128,
    max_annotation_values=256,
    max_binary_keys=64,
    cms_width=1 << 10,
    hll_p=8,
    quantile_buckets=512,
)


def small_store() -> TpuSpanStore:
    return TpuSpanStore(SMALL)


@pytest.mark.parametrize("name", conformance_test_names())
def test_tpu_store_conformance(name):
    run_conformance_test(name, small_store)


def _rpc(trace_id, span_id, parent, client, server, t0, t1, name="call"):
    cl = Endpoint(1, 1, client)
    sv = Endpoint(2, 2, server)
    mid = (t0 + t1) // 2
    return Span(
        trace_id, name, span_id, parent,
        (
            Annotation(t0, "cs", cl),
            Annotation(t0 + 1, "sr", sv),
            Annotation(mid, "custom-work", sv),
            Annotation(t1 - 1, "ss", sv),
            Annotation(t1, "cr", cl),
        ),
        (BinaryAnnotation("http.uri", b"/x", host=sv),),
    )


class TestAnalytics:
    def make_loaded(self):
        store = small_store()
        spans = []
        for t in range(20):
            tid = 1000 + t
            spans.append(_rpc(tid, 1, None, "web", "api", 100, 1100))
            spans.append(_rpc(tid, 2, 1, "api", "db", 200, 700))
        store.apply(spans)
        return store

    def test_dependency_links_from_streaming_join(self):
        # Shared-span model: the root span (client web / server api) has
        # no parent, so the only parent→child join is span1→span2, i.e.
        # (api → db) — matching ZipkinAggregateJob's parent×child join.
        store = self.make_loaded()
        deps = store.get_dependencies()
        got = {(l.parent, l.child): l for l in deps.links}
        assert set(got) == {("api", "db")}
        assert got[("api", "db")].duration_moments.count == 20
        assert got[("api", "db")].duration_moments.mean == pytest.approx(500.0)

    def test_service_quantiles(self):
        store = self.make_loaded()
        p50 = store.service_duration_quantiles("db", [0.5])
        assert p50 is not None
        assert p50[0] == pytest.approx(500.0, rel=0.03)

    def test_unique_trace_estimate(self):
        store = self.make_loaded()
        est = store.estimated_unique_traces()
        assert abs(est - 20) <= 3

    def test_top_annotations(self):
        store = self.make_loaded()
        top = store.top_annotations("db", k=3)
        assert top and top[0][0] == "custom-work"
        assert top[0][1] == 20

    def test_counters(self):
        store = self.make_loaded()
        c = store.counters()
        assert c["spans_seen"] == 40
        assert c["batches"] >= 1

    def test_multi_batch_accumulation(self):
        store = small_store()
        store.apply([_rpc(1, 1, None, "w", "a", 0, 1000),
                     _rpc(1, 2, 1, "a", "b", 100, 200)])
        store.apply([_rpc(2, 1, None, "w", "a", 0, 1000),
                     _rpc(2, 2, 1, "a", "b", 100, 400)])
        deps = store.get_dependencies()
        link = {(l.parent, l.child): l for l in deps.links}[("a", "b")]
        assert link.duration_moments.count == 2

    def test_cross_batch_parent_child_links(self):
        """Parent and child arriving in separate payloads must still
        produce their dependency link (ADVICE r1: the within-batch-only
        join silently dropped these — the normal case across services)."""
        store = small_store()
        store.apply([_rpc(7, 1, None, "w", "a", 0, 1000)])  # parent alone
        store.apply([_rpc(7, 2, 1, "a", "b", 100, 300)])    # child later
        store.apply([_rpc(8, 2, 1, "a", "b", 100, 500)])    # child first
        store.apply([_rpc(8, 1, None, "w", "a", 0, 1000)])  # parent later
        deps = store.get_dependencies()
        link = {(l.parent, l.child): l for l in deps.links}[("a", "b")]
        assert link.duration_moments.count == 2
        assert link.duration_moments.mean == pytest.approx(300.0)

    def test_cross_batch_links_survive_archive(self):
        """Links counted before eviction stay counted after the child is
        evicted from the ring (archive watermark path)."""
        cfg = StoreConfig(
            capacity=8, ann_capacity=64, bann_capacity=32,
            max_services=8, max_span_names=16, max_annotation_values=32,
            max_binary_keys=8, cms_width=256, hll_p=4, quantile_buckets=64,
        )
        store = TpuSpanStore(cfg)
        # Parent and child in separate batches, then enough traffic to
        # wrap the 8-row ring several times.
        store.apply([_rpc(1, 1, None, "w", "a", 0, 1000)])
        store.apply([_rpc(1, 2, 1, "a", "b", 100, 300)])
        for t in range(2, 34):
            store.apply([_rpc(t, 1, None, "w", "s", 0, 50)])
        deps = store.get_dependencies()
        got = {(l.parent, l.child): l for l in deps.links}
        assert ("a", "b") in got
        assert got[("a", "b")].duration_moments.count == 1


class TestReviewRegressions:
    def test_str_binary_value_found_by_bytes_query(self):
        # Stored as str, queried as bytes (the SPI's wire form): must hit.
        store = small_store()
        ep = Endpoint(1, 1, "svc")
        store.apply([
            Span(7, "op", 1, None, (Annotation(10, "x", ep),),
                 (BinaryAnnotation("http.method", "GET", host=ep),))
        ])
        ids = store.get_trace_ids_by_annotation(
            "svc", "http.method", b"GET", 100, 10
        )
        assert [i.trace_id for i in ids] == [7]

    def test_unsigned_trace_ids_roundtrip_queries(self):
        big = 2**63 + 5  # unsigned wire id; stored signed
        store = small_store()
        ep = Endpoint(1, 1, "svc")
        store.apply([Span(big, "op", 1, None, (Annotation(10, "x", ep),), ())])
        assert store.traces_exist([big]) == {big}
        found = store.get_spans_by_trace_ids([big])
        assert len(found) == 1 and len(found[0]) == 1
        durs = store.get_traces_duration([big])
        assert durs and durs[0].trace_id == big

    def test_oversized_batch_rejected_but_apply_chunks(self):
        cfg = StoreConfig(
            capacity=32, ann_capacity=128, bann_capacity=64,
            max_services=8, max_span_names=16, max_annotation_values=32,
            max_binary_keys=8, cms_width=256, hll_p=4, quantile_buckets=64,
        )
        store = TpuSpanStore(cfg)
        from zipkin_tpu.columnar.encode import SpanCodec

        spans = [
            Span(t, "op", 1, None,
                 (Annotation(10, "x", Endpoint(1, 1, "svc")),), ())
            for t in range(40)
        ]
        batch = store.codec.encode(spans)
        with pytest.raises(ValueError):
            store.write_batch(batch, np.ones(40, bool))
        # apply() chunks internally and succeeds (last 32 survive).
        store2 = TpuSpanStore(cfg)
        store2.apply(spans)
        assert store2.counters()["spans_seen"] == 40

    def test_single_span_annotation_overflow_truncated(self):
        """One span with more annotations than the ring holds must be
        truncated (counted), not yielded as-is — an oversized chunk wraps
        the annotation ring and scatters colliding slots in one launch."""
        from zipkin_tpu.columnar.schema import SpanBatch

        cfg = StoreConfig(
            capacity=64, ann_capacity=16, bann_capacity=16,
            max_services=8, max_span_names=16, max_annotation_values=32,
            max_binary_keys=8, cms_width=256, hll_p=4, quantile_buckets=64,
        )
        store = TpuSpanStore(cfg)
        n_ann = 40
        batch = SpanBatch.empty(1, n_ann, 0)
        batch.trace_id[:] = 5
        batch.span_id[:] = 1
        batch.name_id[:] = store.dicts.span_names.encode("op")
        batch.ann_span_idx[:] = 0
        batch.ann_ts[:] = np.arange(n_ann)
        batch.ann_value_id[:] = 1
        chunks = list(store._chunk_columnar(
            batch, np.full(1, -1, np.int32), np.ones(1, bool)
        ))
        assert all(p.n_annotations <= cfg.ann_capacity for p, _, _ in chunks)
        assert store.anns_truncated == n_ann - cfg.ann_capacity
        for part, lc, ix in chunks:
            store.write_batch(part, ix)
        assert store.counters()["spans_seen"] == 1

        # The python slow path (apply) takes the same guard: a fat span
        # is truncated, not the whole batch dropped.
        store2 = TpuSpanStore(cfg)
        fat = Span(7, "op", 1, None, tuple(
            Annotation(100 + i, f"a{i}", Endpoint(1, 1, "svc"))
            for i in range(n_ann)
        ), ())
        store2.apply([fat, Span(8, "op", 2, None,
                                (Annotation(10, "x", Endpoint(1, 1, "svc")),),
                                ())])
        assert store2.counters()["spans_seen"] == 2
        assert store2.anns_truncated > 0
        assert store2.traces_exist([7, 8]) == {7, 8}


class TestRingEviction:
    def test_overwrite_drops_old_traces(self):
        cfg = StoreConfig(
            capacity=8, ann_capacity=64, bann_capacity=32,
            max_services=8, max_span_names=16, max_annotation_values=32,
            max_binary_keys=8, cms_width=256, hll_p=4, quantile_buckets=64,
        )
        store = TpuSpanStore(cfg)
        for t in range(16):
            store.apply([_rpc(t, 1, None, "w", "s", t * 10, t * 10 + 5)])
        # Only the last 8 traces remain addressable.
        assert store.traces_exist(list(range(16))) == set(range(8, 16))
        # Evicted span rows must not satisfy index queries.
        ids = store.get_trace_ids_by_name("w", None, 10**9, 100)
        assert {i.trace_id for i in ids} == set(range(8, 16))
        # Annotations of evicted spans are not returned.
        found = store.get_spans_by_trace_ids([3])
        assert found == []

    def test_sketches_survive_eviction(self):
        cfg = StoreConfig(
            capacity=8, ann_capacity=64, bann_capacity=32,
            max_services=8, max_span_names=16, max_annotation_values=32,
            max_binary_keys=8, cms_width=256, hll_p=8, quantile_buckets=64,
        )
        store = TpuSpanStore(cfg)
        for t in range(32):
            store.apply([_rpc(t, 1, None, "w", "s", 0, 1000),
                         _rpc(t, 2, 1, "s", "d", 100, 200)])
        deps = store.get_dependencies()
        link = {(l.parent, l.child): l for l in deps.links}[("s", "d")]
        assert link.duration_moments.count == 32  # aggregates never evict
        assert store.counters()["spans_seen"] == 64


# -- pinned-trace retention (SpanStore.scala:66, web pin Handlers.scala:490)


def _mk_span(tid, sid, ts, svc="pinned-svc"):
    ep = Endpoint(1, 80, svc)
    return Span(tid, "op", sid, None,
                (Annotation(ts, "sr", ep), Annotation(ts + 5, "custom", ep)),
                ())


def _flood(store, n_spans, base_sid=10_000):
    ep = Endpoint(2, 80, "noise")
    chunk = []
    for i in range(n_spans):
        chunk.append(Span(
            5_000_000 + i, "noise-op", base_sid + i, None,
            (Annotation(50 + i, "sr", ep),), (),
        ))
        if len(chunk) == 256:
            store.apply(chunk)
            chunk = []
    if chunk:
        store.apply(chunk)


def test_pinned_trace_survives_ring_eviction():
    store = small_store()
    tid = 424242
    spans = [_mk_span(tid, s, ts) for s, ts in ((1, 10), (2, 20), (3, 30))]
    store.apply(spans)
    store.set_time_to_live(tid, 30 * 24 * 3600.0)
    # Post-pin arrival must be banked too.
    store.apply([_mk_span(tid, 4, 40)])
    # Lap the ring twice: every unpinned row is overwritten.
    _flood(store, 2 * SMALL.capacity)
    got = store.get_spans_by_trace_id(tid)
    assert sorted(s.id for s in got) == [1, 2, 3, 4]
    assert tid in store.traces_exist([tid])
    durs = store.get_traces_duration([tid])
    assert durs and durs[0].duration == 45 - 10
    # is_pinned truthfulness: the TTL number AND the data both survive.
    assert store.get_time_to_live(tid) == 30 * 24 * 3600.0


def test_unpin_restores_normal_eviction():
    store = small_store()
    tid = 515151
    store.apply([_mk_span(tid, 1, 10)])
    store.set_time_to_live(tid, 30 * 24 * 3600.0)
    store.set_time_to_live(tid, 1.0)  # unpin
    _flood(store, 2 * SMALL.capacity)
    assert store.get_spans_by_trace_id(tid) == []
    assert store.traces_exist([tid]) == set()


def test_sharded_pinned_trace_survives_eviction():
    import jax
    from jax.sharding import Mesh

    from zipkin_tpu.parallel.shard import ShardedSpanStore

    n = min(8, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:n]), axis_names=("shard",))
    cfg = StoreConfig(
        capacity=128, ann_capacity=512, bann_capacity=256,
        max_services=16, max_span_names=32, max_annotation_values=64,
        max_binary_keys=16, cms_width=256, hll_p=6, quantile_buckets=128,
    )
    store = ShardedSpanStore(mesh, cfg)
    tid = 909090
    store.apply([_mk_span(tid, 1, 10), _mk_span(tid, 2, 20)])
    store.set_time_to_live(tid, 30 * 24 * 3600.0)
    _flood(store, 2 * n * cfg.capacity)
    got = store.get_spans_by_trace_id(tid)
    assert sorted(s.id for s in got) == [1, 2]
    assert tid in store.traces_exist([tid])


def test_hot_trace_candidate_escalation():
    """One trace with more matching spans than the initial top-k window
    (64): the escalating fetch must still surface the older cold trace
    — and the result must match the in-memory oracle exactly."""
    from zipkin_tpu.store.memory import InMemorySpanStore

    ep = Endpoint(9, 80, "hotsvc")
    hot = [Span(111, "h", 10_000 + i, None,
                (Annotation(1000 + i, "sr", ep),), ())
           for i in range(300)]
    cold = [Span(222, "c", 99, None, (Annotation(5, "sr", ep),), ())]
    tpu = small_store()
    mem = InMemorySpanStore()
    for st in (tpu, mem):
        st.apply(hot + cold)
    want = mem.get_trace_ids_by_name("hotsvc", None, 2**62, 2)
    got = tpu.get_trace_ids_by_name("hotsvc", None, 2**62, 2)
    assert [(i.trace_id, i.timestamp) for i in got] == \
           [(i.trace_id, i.timestamp) for i in want]
    assert [i.trace_id for i in got] == [111, 222]
