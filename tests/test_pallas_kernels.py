"""Pallas kernel parity tests (interpret mode on the CPU backend)."""

import jax.numpy as jnp
import numpy as np
import pytest

from zipkin_tpu.ops import pallas_kernels as pk


class TestFlatHistogram:
    def test_matches_xla_scatter(self):
        rng = np.random.default_rng(0)
        m = 1024
        idx = rng.integers(-1, m, size=3000).astype(np.int32)
        w = rng.random(3000).astype(np.float32)
        counts = jnp.zeros(m, jnp.float32)
        got = pk.histogram_update(counts, jnp.asarray(idx), jnp.asarray(w),
                                  tile=256)
        want = pk.scatter_histogram_xla(counts, jnp.asarray(idx),
                                        jnp.asarray(w))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5)

    def test_int_counts(self):
        idx = jnp.asarray([0, 5, 5, 127, 128, -1], jnp.int32)
        counts = jnp.zeros(256, jnp.int32)
        got = pk.histogram_update(counts, idx, tile=128)
        want = np.zeros(256, np.int32)
        for i in [0, 5, 5, 127, 128]:
            want[i] += 1
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_accumulates_across_tiles(self):
        # Same bucket hit from several tiles must sum, not overwrite.
        idx = jnp.full(1000, 7, jnp.int32)
        got = pk.histogram_update(jnp.zeros(128, jnp.float32), idx, tile=128)
        assert float(got[7]) == 1000.0

    def test_2d_counts_shape_preserved(self):
        counts = jnp.zeros((4, 128), jnp.float32)
        idx = jnp.asarray([0, 129, 511], jnp.int32)
        got = pk.histogram_update(counts, idx, tile=128)
        assert got.shape == (4, 128)
        assert float(got[0, 0]) == 1 and float(got[1, 1]) == 1
        assert float(got[3, 127]) == 1


class TestCmsUpdate:
    def test_matches_ops_cms(self):
        from zipkin_tpu.ops import cms
        from zipkin_tpu.ops.hashing import split64

        keys = np.arange(50, dtype=np.int64) * 7919
        hi, lo = split64(keys)
        sk = cms.init(depth=4, width=1 << 10)
        want = cms.update(sk, hi, lo).counts
        idx = cms._indices(sk, jnp.asarray(hi), jnp.asarray(lo))
        got = pk.cms_update(sk.counts, idx, tile=128)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
