"""Pallas kernel parity tests (interpret mode on the CPU backend)."""

import jax.numpy as jnp
import numpy as np
import pytest

from zipkin_tpu.ops import pallas_kernels as pk


class TestFlatHistogram:
    def test_matches_xla_scatter(self):
        rng = np.random.default_rng(0)
        m = 1024
        idx = rng.integers(-1, m, size=3000).astype(np.int32)
        w = rng.random(3000).astype(np.float32)
        counts = jnp.zeros(m, jnp.float32)
        got = pk.histogram_update(counts, jnp.asarray(idx), jnp.asarray(w),
                                  tile=256)
        want = pk.scatter_histogram_xla(counts, jnp.asarray(idx),
                                        jnp.asarray(w))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5)

    def test_int_counts(self):
        idx = jnp.asarray([0, 5, 5, 127, 128, -1], jnp.int32)
        counts = jnp.zeros(256, jnp.int32)
        got = pk.histogram_update(counts, idx, tile=128)
        want = np.zeros(256, np.int32)
        for i in [0, 5, 5, 127, 128]:
            want[i] += 1
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_accumulates_across_tiles(self):
        # Same bucket hit from several tiles must sum, not overwrite.
        idx = jnp.full(1000, 7, jnp.int32)
        got = pk.histogram_update(jnp.zeros(128, jnp.float32), idx, tile=128)
        assert float(got[7]) == 1000.0

    def test_2d_counts_shape_preserved(self):
        counts = jnp.zeros((4, 128), jnp.float32)
        idx = jnp.asarray([0, 129, 511], jnp.int32)
        got = pk.histogram_update(counts, idx, tile=128)
        assert got.shape == (4, 128)
        assert float(got[0, 0]) == 1 and float(got[1, 1]) == 1
        assert float(got[3, 127]) == 1


class TestCmsUpdate:
    def test_matches_ops_cms(self):
        from zipkin_tpu.ops import cms
        from zipkin_tpu.ops.hashing import split64

        keys = np.arange(50, dtype=np.int64) * 7919
        hi, lo = split64(keys)
        sk = cms.init(depth=4, width=1 << 10)
        want = cms.update(sk, hi, lo).counts
        idx = cms._indices(sk, jnp.asarray(hi), jnp.asarray(lo))
        got = pk.cms_update(sk.counts, idx, tile=128)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestArenaClaimScatter:
    """r12 fused claim+scatter vs the XLA reference formulation: the
    kernel's sequential cursor walk + write-all-in-arrival-order must
    land the bitwise SAME arena as the rank-gated unique plane scatter
    (_index_write's XLA path) — including under in-batch overflow,
    where the kernel overwrites dropped rows instead of skipping
    them."""

    def _xla_reference(self, entries, bucket, pos, depth, vals, valid,
                       n_b):
        import jax

        from zipkin_tpu.store import device as dev

        rank = dev._fifo_ranks(bucket, valid, n_b)
        pos_lo = jax.lax.bitcast_convert_type(pos, jnp.int32)[:, 0]
        b_c = jnp.clip(bucket, 0, n_b - 1)
        pos_b = pos_lo[b_c]
        oob_b = jnp.where(valid, b_c, n_b)
        cnt = jnp.zeros(n_b + 1, jnp.int32).at[oob_b].add(
            1, mode="drop")[:n_b]
        keep = valid & (rank >= cnt[b_c] - depth)
        slot = (b_c * depth).astype(jnp.int32) + (
            (pos_b + rank) % depth)
        return dev._uset_cols64(entries, slot, vals, keep)

    def test_matches_xla_path(self):
        rng = np.random.default_rng(11)
        n_b, depth = 53, 8
        S = n_b * depth
        for n in (7, 300, 1024):
            entries = jnp.asarray(
                rng.integers(-2**62, 2**62, (S, 3)), jnp.int64)
            bucket = jnp.asarray(rng.integers(0, n_b, n), jnp.int32)
            pos = jnp.asarray(rng.integers(0, 500, n_b), jnp.int64)
            valid = jnp.asarray(rng.random(n) < 0.8)
            vals = jnp.asarray(
                rng.integers(-2**62, 2**62, (n, 3)), jnp.int64)
            dvec = jnp.full(n, depth, jnp.int32)
            want = self._xla_reference(entries, bucket, pos, depth,
                                       vals, valid, n_b)
            pos_lo = np.asarray(pos).astype(np.uint64) & 0xFFFFFFFF
            base = jnp.asarray(
                pos_lo[np.clip(np.asarray(bucket), 0, n_b - 1)],
                jnp.int32)
            got = pk.arena_claim_scatter(
                entries, bucket, base,
                bucket.astype(jnp.int64) * depth, dvec, vals, valid,
                n_buckets=n_b, tile=256)
            np.testing.assert_array_equal(np.asarray(want),
                                          np.asarray(got), err_msg=n)

    def test_overflow_single_bucket(self):
        # 100 rows into one depth-4 bucket: the kernel writes all 100
        # in order; the final 4 slots must hold exactly the newest 4
        # rows at the cursor-aligned positions.
        n_b, depth, n = 4, 4, 100
        S = n_b * depth
        entries = jnp.full((S, 3), -1, jnp.int64)
        bucket = jnp.zeros(n, jnp.int32)
        vals = jnp.stack(
            [jnp.arange(n, dtype=jnp.int64)] * 3, axis=-1)
        got = pk.arena_claim_scatter(
            entries, bucket, jnp.zeros(n, jnp.int32),
            jnp.zeros(n, jnp.int64), jnp.full(n, depth, jnp.int32),
            vals, jnp.ones(n, bool), n_buckets=n_b)
        got = np.asarray(got)
        # slots (0+r) % 4 for r=96..99 -> slot r%4 holds row r.
        np.testing.assert_array_equal(got[:4, 0], [96, 97, 98, 99])
        np.testing.assert_array_equal(got[4:, 0], -np.ones(S - 4))

    def test_supported_boundary(self):
        assert pk.arena_scatter_supported(1 << 12, 1 << 10)
        assert not pk.arena_scatter_supported(100_000_000, 800_000)
        assert not pk.arena_scatter_supported(0, 10)
        assert not pk.arena_scatter_supported(1 << 32, 10)

    @pytest.mark.slow
    def test_store_level_identity(self):
        # A use_pallas store must land the bitwise-identical state of
        # the XLA store (the arena fits VMEM at this geometry, so the
        # fused kernel actually engages — counters prove it). Slow
        # lane: the kernel-level fuzz above is the bitwise proof in
        # tier-1; this is the whole-store integration twin.
        from zipkin_tpu.store import device as dev
        from zipkin_tpu.store.tpu import TpuSpanStore
        from zipkin_tpu.testing.crash import states_bitwise_equal
        from zipkin_tpu.tracegen import generate_traces

        base = dict(
            capacity=1 << 10, ann_capacity=1 << 11,
            bann_capacity=1 << 10, max_services=16, max_span_names=32,
            max_annotation_values=64, max_binary_keys=32,
            cms_width=1 << 8, hll_p=6, quantile_buckets=64,
        )
        cfg_x = dev.StoreConfig(**base, rank_path="argsort")
        cfg_p = dev.StoreConfig(**base, rank_path="argsort",
                                use_pallas=True)
        traces = generate_traces(n_traces=28, max_depth=3,
                                 n_services=8)
        spans = [s for t in traces for s in t][:170]
        stores = []
        for cfg in (cfg_x, cfg_p):
            st = TpuSpanStore(cfg)
            for i in range(0, len(spans), 64):
                st.apply(spans[i:i + 64])
            stores.append(st)
        assert states_bitwise_equal(stores[0].state, stores[1].state)
        assert stores[1].counters()["scatter_path_pallas"] == 1.0
        assert stores[0].counters()["scatter_path_pallas"] == 0.0
