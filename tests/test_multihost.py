"""Multi-host span routing across REAL processes (VERDICT missing #1).

Three rounds of routing-math unit tests never crossed a process
boundary. This spawns a coordinator + worker pair of forced-CPU
processes joined through ``jax.distributed.initialize``, builds the
GLOBAL shard mesh in each, routes one shared deterministic span set
with ``parallel.multihost.route_spans``, and proves the partition
property the data plane depends on: every span lands on exactly one
host, that host owns the span's shard, and the union across hosts is
the whole set. Marked ``slow`` (spawns subprocesses and a distributed
coordination service).
"""

import json
import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import importlib.util, json, os, sys

# Two virtual CPU devices per process -> a 4-shard global mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2"
)

coordinator, pid = sys.argv[1], int(sys.argv[2])

# Load multihost by file path: importing the zipkin_tpu.parallel
# PACKAGE pulls in shard.py, whose module-level jnp constants
# initialize the backend — and jax.distributed.initialize must run
# before ANY computation. multihost.py itself is numpy-pure.
root = os.environ["ZIPKIN_TPU_ROOT"]
spec = importlib.util.spec_from_file_location(
    "mh", os.path.join(root, "zipkin_tpu", "parallel", "multihost.py"))
multihost = importlib.util.module_from_spec(spec)
spec.loader.exec_module(multihost)
from zipkin_tpu.models.span import Span

multihost.initialize(coordinator, num_processes=2, process_id=pid)

import jax

mesh = multihost.global_mesh()
n_shards = int(mesh.shape["shard"])
local = multihost.local_shard_ids(mesh)

# The SAME deterministic span set in both processes (the producer
# view); each process keeps only what it owns (the consumer view).
spans = [Span(tid * 2654435761 % (1 << 62) + 1, "op", 1, None, (), ())
         for tid in range(1, 65)]
kept = multihost.route_spans(spans, n_shards, keep=local)

print(json.dumps({
    "pid": pid,
    "n_devices": len(jax.devices()),
    "n_local_devices": len(jax.local_devices()),
    "n_shards": n_shards,
    "local_shards": sorted(local),
    "partitions": sorted(multihost.partitions_for_process(mesh)),
    "kept": {str(sid): sorted(s.trace_id for s in group)
             for sid, group in kept.items()},
    "all_tids": sorted(s.trace_id for s in spans),
}), flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_distributed_routing(tmp_path):
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root
    env["ZIPKIN_TPU_ROOT"] = root
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, coordinator, str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.skip("distributed coordination did not converge "
                        "in this environment")
        if p.returncode != 0:
            if ("UNAVAILABLE" in err or "DEADLINE_EXCEEDED" in err
                    or "barrier" in err.lower()):
                pytest.skip(f"no multi-process fabric here: "
                            f"{err[-500:]}")
            raise AssertionError(err[-2000:])
        outs.append(json.loads(out.strip().splitlines()[-1]))

    by_pid = {o["pid"]: o for o in outs}
    a, b = by_pid[0], by_pid[1]
    # Both processes saw the same 4-device global view, 2 local each.
    assert a["n_devices"] == b["n_devices"] == 4
    assert a["n_local_devices"] == b["n_local_devices"] == 2
    assert a["n_shards"] == b["n_shards"] == 4
    # Local shard ownership partitions the mesh.
    assert sorted(a["local_shards"] + b["local_shards"]) == [0, 1, 2, 3]
    assert not set(a["local_shards"]) & set(b["local_shards"])
    # Kafka partition mapping is exactly shard ownership.
    assert a["partitions"] == a["local_shards"]
    assert b["partitions"] == b["local_shards"]
    # Routing delivered every span to EXACTLY ONE host, that host owns
    # the span's shard, and nothing was lost or duplicated.
    from zipkin_tpu.parallel.multihost import shard_of

    assert a["all_tids"] == b["all_tids"]
    seen = []
    for o in (a, b):
        local = set(o["local_shards"])
        for sid_str, tids in o["kept"].items():
            assert int(sid_str) in local
            for tid in tids:
                assert shard_of(tid, o["n_shards"]) == int(sid_str)
            seen.extend(tids)
    assert sorted(seen) == a["all_tids"]
