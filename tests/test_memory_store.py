"""Run the conformance suite against the in-memory reference store
(reference: InMemorySpanStoreTest via SpanStoreValidator)."""

import pytest

from zipkin_tpu.store.memory import InMemorySpanStore
from zipkin_tpu.testing.conformance import (
    conformance_test_names,
    run_conformance_test,
)


@pytest.mark.parametrize("name", conformance_test_names())
def test_memory_store_conformance(name):
    run_conformance_test(name, InMemorySpanStore)
