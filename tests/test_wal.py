"""Durable write-ahead log (zipkin_tpu.wal): framing, policies,
torn-tail semantics, the unit record codec, deterministic recovery,
slab integrity, and the collector's quiesce ordering.

Process-death coverage (real SIGKILL at named points) lives in
tests/test_crash.py; this file proves the same contracts at the
library layer, where every failure mode can be constructed byte by
byte.
"""

import os
import struct
import zipfile

import numpy as np
import pytest

from zipkin_tpu import checkpoint
from zipkin_tpu.checkpoint import CorruptSlabError
from zipkin_tpu.columnar.dictionary import DictionarySet
from zipkin_tpu.store.device import StoreConfig
from zipkin_tpu.store.tpu import TpuSpanStore
from zipkin_tpu.testing.crash import (
    build_crash_store,
    crash_batches,
    states_bitwise_equal,
)
from zipkin_tpu.wal import (
    FsyncPolicy,
    WalReplayError,
    WriteAheadLog,
    recover,
    replay_into,
)
from zipkin_tpu.wal import record as walrec
from zipkin_tpu.wal.log import _MAGIC, _REC


# ---------------------------------------------------------------------------
# Log framing + policies (byte-level, no device)
# ---------------------------------------------------------------------------


def _payloads(n, size=64):
    return [bytes([i % 251]) * size + i.to_bytes(4, "big")
            for i in range(n)]


class TestLogFraming:
    def test_append_replay_roundtrip(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path / "w"), fsync="batch")
        pays = _payloads(7)
        seqs = [wal.append(p) for p in pays]
        assert seqs == list(range(1, 8))
        assert wal.last_seq == 7
        # batch policy: append returning means durable
        assert wal.durable_seq == 7
        got = list(wal.replay(0))
        assert got == list(zip(range(1, 8), pays))
        # from_seq skips the covered prefix
        assert list(wal.replay(5)) == list(zip((6, 7), pays[5:]))
        wal.close()

    def test_reopen_resumes_sequences(self, tmp_path):
        d = str(tmp_path / "w")
        wal = WriteAheadLog(d, fsync="batch")
        for p in _payloads(3):
            wal.append(p)
        wal.close()
        wal2 = WriteAheadLog(d, fsync="batch")
        assert wal2.last_seq == 3
        assert wal2.append(b"next") == 4
        assert [s for s, _ in wal2.replay(0)] == [1, 2, 3, 4]
        wal2.close()

    def test_segment_roll_and_cross_segment_replay(self, tmp_path):
        d = str(tmp_path / "w")
        wal = WriteAheadLog(d, fsync="off", segment_bytes=1 << 12,
                            compress=False)
        pays = _payloads(40, size=300)  # ~13 segments
        for p in pays:
            wal.append(p)
        wal.sync()
        segs = [n for n in os.listdir(d) if n.endswith(".seg")]
        assert len(segs) > 3
        assert [p for _, p in wal.replay(0)] == pays
        wal.close()
        # a fresh open over many segments sees the same prefix
        wal2 = WriteAheadLog(d, fsync="off")
        assert wal2.last_seq == 40
        assert [p for _, p in wal2.replay(35)] == pays[35:]
        wal2.close()

    def test_torn_tail_garbage_is_cut(self, tmp_path):
        d = str(tmp_path / "w")
        wal = WriteAheadLog(d, fsync="batch")
        pays = _payloads(5)
        for p in pays:
            wal.append(p)
        wal.close()
        seg = os.path.join(d, sorted(os.listdir(d))[0])
        with open(seg, "ab") as f:
            f.write(b"\x00\x00\x00\x10partial-frame-garbage")
        wal2 = WriteAheadLog(d, fsync="batch")
        assert wal2.last_seq == 5
        assert wal2.torn_records_cut >= 1
        assert [p for _, p in wal2.replay(0)] == pays
        # the cut is PHYSICAL: a third open sees a clean file
        wal2.close()
        wal3 = WriteAheadLog(d, fsync="batch")
        assert wal3.torn_records_cut == 0
        wal3.close()

    def test_torn_mid_record_truncates_to_prefix(self, tmp_path):
        d = str(tmp_path / "w")
        wal = WriteAheadLog(d, fsync="batch", compress=False)
        pays = _payloads(5)
        for p in pays:
            wal.append(p)
        wal.close()
        seg = os.path.join(d, sorted(os.listdir(d))[0])
        # chop into the final record's payload
        with open(seg, "r+b") as f:
            f.truncate(os.path.getsize(seg) - 10)
        wal2 = WriteAheadLog(d, fsync="batch")
        assert wal2.last_seq == 4
        assert [p for _, p in wal2.replay(0)] == pays[:4]
        wal2.close()

    def test_crc_corrupt_middle_record_cuts_everything_after(
            self, tmp_path):
        d = str(tmp_path / "w")
        wal = WriteAheadLog(d, fsync="off", segment_bytes=1 << 12,
                            compress=False)
        pays = _payloads(30, size=300)
        for p in pays:
            wal.append(p)
        wal.sync()
        wal.close()
        segs = sorted(n for n in os.listdir(d) if n.endswith(".seg"))
        assert len(segs) >= 3
        victim = os.path.join(d, segs[1])
        # flip one payload byte in the middle segment's first record
        hdr_end = len(_MAGIC) + 4 + len(
            b'{"version":1,"base_seq":%d}' % 0)  # recompute below
        with open(victim, "r+b") as f:
            head = f.read(len(_MAGIC) + 4)
            (hlen,) = struct.unpack(">I", head[len(_MAGIC):])
            hdr_end = len(_MAGIC) + 4 + hlen
            f.seek(hdr_end + _REC.size + 5)
            b = f.read(1)
            f.seek(hdr_end + _REC.size + 5)
            f.write(bytes([b[0] ^ 0xFF]))
        wal2 = WriteAheadLog(d, fsync="off")
        # prefix semantics: nothing at or past the corrupt record
        # survives, INCLUDING later (intact) segments
        survivors = [p for _, p in wal2.replay(0)]
        assert survivors == pays[:len(survivors)]
        assert len(survivors) < 30
        assert wal2.torn_records_cut >= 1
        names = sorted(n for n in os.listdir(d) if n.endswith(".seg"))
        assert names[-1] == segs[1] or len(names) < len(segs)
        wal2.close()

    def test_sequence_hole_between_segments_cuts(self, tmp_path):
        d = str(tmp_path / "w")
        wal = WriteAheadLog(d, fsync="off", segment_bytes=1 << 12,
                            compress=False)
        for p in _payloads(30, size=300):
            wal.append(p)
        wal.sync()
        wal.close()
        segs = sorted(n for n in os.listdir(d) if n.endswith(".seg"))
        assert len(segs) >= 3
        os.remove(os.path.join(d, segs[1]))  # hole in the middle
        wal2 = WriteAheadLog(d, fsync="off")
        first_n = len(list(wal2.replay(0)))
        assert 0 < first_n < 30  # only segment 0's prefix survives
        assert wal2.torn_records_cut >= 1
        wal2.close()

    def test_compressed_payload_roundtrip(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path / "w"), fsync="batch",
                            compress=True)
        pay = b"abcdefgh" * 4096  # 32 KB, highly compressible
        wal.append(pay)
        seg = os.path.join(wal.directory, sorted(
            os.listdir(wal.directory))[0])
        assert os.path.getsize(seg) < len(pay) // 4
        assert list(wal.replay(0)) == [(1, pay)]
        wal.close()


class TestPoliciesAndTruncation:
    def test_interval_group_commit_advances_durable(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path / "w"), fsync="interval",
                            interval_s=0.01)
        seq = wal.append(b"x" * 100)
        assert wal.wait_durable(seq, timeout=10.0)
        assert wal.durable_seq >= seq
        wal.close()

    def test_off_policy_tracks_append_frontier(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path / "w"), fsync="off")
        seq = wal.append(b"y" * 100)
        assert wal.durable_seq == seq  # page-cache durability
        wal.close()

    def test_sync_is_an_explicit_barrier(self, tmp_path):
        # a group-commit cadence too slow for the test must not matter
        wal = WriteAheadLog(str(tmp_path / "w"), fsync="interval",
                            interval_s=30.0)
        seq = wal.append(b"z" * 100)
        wal.sync()
        assert wal.durable_seq >= seq
        wal.close()

    def test_bad_policy_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            WriteAheadLog(str(tmp_path / "w"), fsync="sometimes")

    def test_truncate_deletes_covered_segments(self, tmp_path):
        d = str(tmp_path / "w")
        wal = WriteAheadLog(d, fsync="off", segment_bytes=1 << 12,
                            compress=False)
        pays = _payloads(30, size=300)
        for p in pays:
            wal.append(p)
        wal.sync()
        before = len([n for n in os.listdir(d) if n.endswith(".seg")])
        removed = wal.truncate(upto_seq=20)
        assert removed >= 1
        after = len([n for n in os.listdir(d) if n.endswith(".seg")])
        assert after < before
        # replay past the checkpoint frontier still intact
        assert [p for _, p in wal.replay(20)] == pays[20:]
        # appends continue normally after truncation
        assert wal.append(b"tail") == 31
        wal.close()

    def test_truncate_everything_rolls_active_segment(self, tmp_path):
        d = str(tmp_path / "w")
        wal = WriteAheadLog(d, fsync="batch", compress=False)
        for p in _payloads(5):
            wal.append(p)
        wal.truncate(upto_seq=5)
        assert list(wal.replay(0)) == []
        assert wal.append(b"after") == 6  # sequences never reset
        assert list(wal.replay(0)) == [(6, b"after")]
        wal.close()

    def test_truncate_on_reopened_log_preserves_sequence_chain(
            self, tmp_path):
        """A reopened log that has NOT appended yet (file not open —
        the daemon's read-mostly window after boot replay) must still
        roll before a full truncation: deleting every segment would
        leave no record of _next_seq, the next open would restart at
        seq 1 below the checkpoint's applied frontier, and recovery
        would silently skip that many durably-acked records."""
        d = str(tmp_path / "w")
        wal = WriteAheadLog(d, fsync="off")
        for p in _payloads(5):
            wal.append(p)
        wal.close()
        re = WriteAheadLog(d, fsync="off")  # replay-only: no appends
        assert re.last_seq == 5
        re.truncate(upto_seq=5)  # the periodic checkpoint fires
        re.close()
        again = WriteAheadLog(d, fsync="off")
        assert again.last_seq == 5  # chain survived the full wipe
        assert again.append(b"after") == 6
        again.close()

    def test_append_after_close_raises(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path / "w"), fsync="batch")
        wal.close()
        with pytest.raises(RuntimeError):
            wal.append(b"late")

    def test_truncate_respects_follower_cursor(self, tmp_path):
        """The shipping retention pin: a registered follower cursor
        clamps truncation so no un-fetched record's segment is ever
        deleted — then releases cleanly when the cursor advances or
        drops (docs/REPLICATION.md)."""
        d = str(tmp_path / "w")
        wal = WriteAheadLog(d, fsync="off", segment_bytes=1 << 12,
                            compress=False)
        pays = _payloads(30, size=300)
        for p in pays:
            wal.append(p)
        wal.sync()
        wal.register_cursor("f1", 4)
        before = len([n for n in os.listdir(d) if n.endswith(".seg")])
        wal.truncate(upto_seq=20)  # clamped to the cursor (4)
        # Everything past the cursor is still replayable in full.
        assert [p for _, p in wal.replay(4)] == pays[4:]
        assert wal.first_available_seq() <= 5
        # Cursor catches up: the covered prefix can now go.
        wal.advance_cursor("f1", 20)
        removed = wal.truncate(upto_seq=20)
        assert removed >= 1
        assert [p for _, p in wal.replay(20)] == pays[20:]
        # A re-register can never move a pin BACKWARD.
        wal.register_cursor("f1", 3)
        assert wal.cursors()["f1"] == 20
        # Dropped cursor: truncation behaves exactly as before.
        wal.drop_cursor("f1")
        wal.truncate(upto_seq=30)
        assert list(wal.replay(0)) == []
        after = len([n for n in os.listdir(d) if n.endswith(".seg")])
        assert after < before
        assert wal.append(b"tail") == 31  # chain intact
        wal.close()

    def test_unpinned_log_truncates_exactly_as_before(self, tmp_path):
        """No cursors + retain_bytes=0 must reproduce the historical
        truncation byte-for-byte: same segments deleted, same
        survivors, against a twin log driven identically."""
        pays = _payloads(30, size=300)

        def drive(name, **kw):
            w = WriteAheadLog(str(tmp_path / name), fsync="off",
                              segment_bytes=1 << 12, compress=False,
                              **kw)
            for p in pays:
                w.append(p)
            w.sync()
            removed = w.truncate(upto_seq=20)
            segs = sorted(os.path.basename(s.path)
                          for s in w._segments)
            tail = [p for _, p in w.replay(0)]
            w.close()
            return removed, segs, tail

        base = drive("plain")
        twin = drive("twin", retain_bytes=0)
        assert base == twin

    def test_retain_bytes_keeps_covered_tail(self, tmp_path):
        """--wal-retain-bytes: the newest covered segments survive
        truncation up to the byte floor, so a reconnecting follower
        catches up from the log instead of re-anchoring."""
        d = str(tmp_path / "w")
        pays = _payloads(30, size=300)
        wal = WriteAheadLog(d, fsync="off", segment_bytes=1 << 12,
                            compress=False, retain_bytes=1 << 30)
        for p in pays:
            wal.append(p)
        wal.sync()
        # Everything is covered, but the (huge) floor protects it all.
        assert wal.truncate(upto_seq=30) == 0
        assert [p for _, p in wal.replay(0)] == pays
        # Shrink the floor to ~one segment: older segments now go,
        # the newest stay.
        wal.retain_bytes = 1 << 12
        removed = wal.truncate(upto_seq=30)
        assert removed >= 1
        kept = [p for _, p in wal.replay(0)]
        assert kept == pays[len(pays) - len(kept):]  # a strict suffix
        assert kept  # floor kept at least the newest segment
        wal.close()


class _HalfWriteFile:
    """Wraps the segment file: the first write lands HALF the frame
    then raises (the ENOSPC shape) — later writes pass through."""

    def __init__(self, f):
        self._f = f
        self.fail = True

    def write(self, b):
        if self.fail:
            self.fail = False
            self._f.write(b[:len(b) // 2])
            self._f.flush()
            raise OSError(28, "No space left on device")
        return self._f.write(b)

    def __getattr__(self, name):
        return getattr(self._f, name)


class TestWriteFailures:
    def test_failed_append_rolls_back_the_torn_frame(self, tmp_path):
        from zipkin_tpu.wal.log import WalDurabilityError

        d = str(tmp_path / "w")
        wal = WriteAheadLog(d, fsync="batch", compress=False)
        pays = _payloads(3)
        wal.append(pays[0])
        wal._file = _HalfWriteFile(wal._file)
        # the failed append surfaces (no ack) and did NOT consume a seq
        with pytest.raises(WalDurabilityError):
            wal.append(pays[1])
        # the torn half-frame was truncated away: the next append gets
        # seq 2 and a crash+reopen sees a clean two-record prefix —
        # without the rollback, this append would sit past torn bytes
        # and be silently cut at recovery despite being acked
        assert wal.append(pays[2]) == 2
        wal.close()
        wal2 = WriteAheadLog(d, fsync="batch")
        assert wal2.torn_records_cut == 0
        assert [p for _, p in wal2.replay(0)] == [pays[0], pays[2]]
        wal2.close()

    def test_unrollbackable_append_failure_poisons_the_log(
            self, tmp_path):
        from zipkin_tpu.wal.log import WalDurabilityError

        wal = WriteAheadLog(str(tmp_path / "w"), fsync="batch",
                            compress=False)
        wal.append(b"ok" * 50)
        broken = _HalfWriteFile(wal._file)
        broken.truncate = lambda *_: (_ for _ in ()).throw(
            OSError("truncate failed too"))
        wal._file = broken
        with pytest.raises(WalDurabilityError):
            wal.append(b"x" * 100)
        # torn bytes are still on disk and could not be removed: every
        # later append would be silently cut at recovery — refuse all
        with pytest.raises(WalDurabilityError, match="poisoned"):
            wal.append(b"y" * 100)

    def test_group_commit_survives_fsync_errors_and_surfaces_them(
            self, tmp_path, monkeypatch):
        import time as _t

        from zipkin_tpu.wal import log as wal_log
        from zipkin_tpu.wal.log import WalDurabilityError

        wal = WriteAheadLog(str(tmp_path / "w"), fsync="interval",
                            interval_s=0.01)
        real_fsync = wal_log.os.fsync
        failing = [True]

        def flaky_fsync(fd):
            if failing[0]:
                raise OSError(5, "Input/output error")
            return real_fsync(fd)

        monkeypatch.setattr(wal_log.os, "fsync", flaky_fsync)
        seq = wal.append(b"z" * 100)
        # while fsync fails, the acker is told — not left to time out
        # against a silently dead group-commit thread
        with pytest.raises(WalDurabilityError):
            deadline = _t.monotonic() + 10.0
            while _t.monotonic() < deadline:
                if wal.wait_durable(seq, timeout=0.2):
                    raise AssertionError("became durable while "
                                         "fsync was failing")
        # the error was TRANSIENT: the sync thread retried, recovered,
        # and the frontier advances
        failing[0] = False
        assert wal.wait_durable(seq, timeout=10.0)
        assert wal.durable_seq >= seq
        wal.close()


# ---------------------------------------------------------------------------
# Unit record codec + dictionary delta lineage
# ---------------------------------------------------------------------------


class TestRecordCodec:
    def _unit(self):
        """One real stage-1 launch group via the columnar generator."""
        from zipkin_tpu.tracegen import ColumnarTraceGen

        dicts = DictionarySet()
        before = walrec.dict_sizes(dicts)
        gen = ColumnarTraceGen(dicts, n_services=4, n_span_names=8,
                               spans_per_trace=3)
        group = [gen.next_batch(4), gen.next_batch(3)]
        return dicts, before, group

    def test_encode_decode_roundtrip(self):
        dicts, before, group = self._unit()
        sizes, deltas = walrec.dump_dict_deltas(dicts, before)
        payload = walrec.encode_unit(group, before, deltas)
        got_group, got_before, got_deltas = walrec.decode_unit(payload)
        assert got_before == before
        assert len(got_group) == len(group)
        cols = (type(group[0][0]).SPAN_COLUMNS
                + type(group[0][0]).ANN_COLUMNS
                + type(group[0][0]).BANN_COLUMNS)
        for (b1, lc1, ix1), (b2, lc2, ix2) in zip(group, got_group):
            for col in cols:
                np.testing.assert_array_equal(
                    getattr(b1, col), getattr(b2, col), err_msg=col)
            np.testing.assert_array_equal(lc1, lc2)
            np.testing.assert_array_equal(ix1, ix2)
        # the delta rebuilds identical id assignment in a fresh set
        fresh = DictionarySet()
        walrec.apply_dict_deltas(fresh, got_before, got_deltas)
        for name in walrec.DICT_NAMES:
            assert (getattr(fresh, name).values()
                    == getattr(dicts, name).values()), name

    def test_unknown_version_fails_fast(self):
        dicts, before, group = self._unit()
        _, deltas = walrec.dump_dict_deltas(dicts, before)
        payload = bytearray(walrec.encode_unit(group, before, deltas))
        # bump the meta version in place
        payload[payload.index(b'"v":1') + 4] = ord("9")
        with pytest.raises(WalReplayError, match="version"):
            walrec.decode_unit(bytes(payload))

    def test_delta_against_shorter_dicts_is_lineage_error(self):
        dicts = DictionarySet()
        dicts.services.encode("svc-a")
        sizes, deltas = walrec.dump_dict_deltas(
            dicts, [1, 0, 0, 0, 0, 0])
        fresh = DictionarySet()  # has 0 services, record expects 1
        with pytest.raises(WalReplayError, match="lineage"):
            walrec.apply_dict_deltas(fresh, [1, 0, 0, 0, 0, 0], deltas)

    def test_conflicting_existing_entry_is_lineage_error(self):
        dicts = DictionarySet()
        dicts.services.encode("svc-a")
        _, deltas = walrec.dump_dict_deltas(dicts, [0, 0, 0, 0, 0, 0])
        other = DictionarySet()
        other.services.encode("svc-DIFFERENT")
        with pytest.raises(WalReplayError, match="lineage"):
            walrec.apply_dict_deltas(other, [0, 0, 0, 0, 0, 0], deltas)

    def test_verified_replay_over_existing_entries(self):
        # checkpoint dictionaries can run AHEAD of the applied seq;
        # replaying a delta whose entries already exist verifies them
        dicts = DictionarySet()
        dicts.services.encode("svc-a")
        _, deltas = walrec.dump_dict_deltas(dicts, [0, 0, 0, 0, 0, 0])
        walrec.apply_dict_deltas(dicts, [0, 0, 0, 0, 0, 0], deltas)
        assert dicts.services.values() == ["svc-a"]  # no duplicate


# ---------------------------------------------------------------------------
# Recovery: checkpoint + tail replay == uncrashed oracle (device path)
# ---------------------------------------------------------------------------


def _drive(store, batches):
    for b in batches:
        store.apply(b)


class TestRecovery:
    def test_checkpoint_plus_tail_replay_is_bitwise_identical(
            self, tmp_path):
        batches = crash_batches(8)
        oracle = build_crash_store(False)
        _drive(oracle, batches)

        store = build_crash_store(False)
        wal = WriteAheadLog(str(tmp_path / "wal"), fsync="off")
        store.attach_wal(wal)
        _drive(store, batches[:4])
        stats = checkpoint.save(store, str(tmp_path / "ckpt"))
        # checkpoint-coordinated truncation ran (covered prefix gone)
        assert "wal_truncated_segments" in stats
        _drive(store, batches[4:])
        wal.sync()
        del store  # crash: HBM state gone, log + snapshot survive

        wal2 = WriteAheadLog(str(tmp_path / "wal"), fsync="off")
        rec, rstats = recover(str(tmp_path / "ckpt"), wal2)
        assert rstats["applied_seq"] == 8
        assert rstats["replayed_records"] == 4
        assert states_bitwise_equal(oracle.state, rec.state)
        # the recovered store keeps journaling: live appends continue
        rec.apply(batches[0])
        assert wal2.last_seq == 9
        wal2.close()

    def test_pipelined_drive_recovers_from_empty(self, tmp_path):
        batches = crash_batches(6)
        oracle = build_crash_store(False)
        _drive(oracle, batches)

        store = build_crash_store(False)
        wal = WriteAheadLog(str(tmp_path / "wal"), fsync="off")
        store.attach_wal(wal)
        store.start_pipeline(4)
        _drive(store, batches)
        store.drain_pipeline()
        wal.sync()
        del store  # crash with NO checkpoint at all

        wal2 = WriteAheadLog(str(tmp_path / "wal"), fsync="off")
        rec, rstats = recover(
            None, wal2, fresh_store=lambda: build_crash_store(False))
        assert rstats["replayed_records"] == 6
        assert states_bitwise_equal(oracle.state, rec.state)
        assert int(wal2.c_replayed.value) == 6
        wal2.close()

    def test_torn_tail_batch_is_absent_not_partial(self, tmp_path):
        batches = crash_batches(6)
        store = build_crash_store(False)
        wal = WriteAheadLog(str(tmp_path / "wal"), fsync="off",
                            compress=False)
        store.attach_wal(wal)
        _drive(store, batches)
        wal.sync()
        wal.close()
        del store
        # tear the final record mid-payload (crash mid-append)
        d = str(tmp_path / "wal")
        seg = os.path.join(d, sorted(os.listdir(d))[-1])
        with open(seg, "r+b") as f:
            f.truncate(os.path.getsize(seg) - 64)

        wal2 = WriteAheadLog(d, fsync="off")
        rec, rstats = recover(
            None, wal2, fresh_store=lambda: build_crash_store(False))
        assert rstats["applied_seq"] == 5
        oracle = build_crash_store(False)
        _drive(oracle, batches[:5])
        assert states_bitwise_equal(oracle.state, rec.state)
        # the torn batch: provably absent, not partially applied
        missing = sorted({s.trace_id for s in batches[5]})
        assert not any(rec.get_spans_by_trace_ids(missing))
        wal2.close()

    def test_foreign_log_lineage_fails_fast(self, tmp_path):
        batches = crash_batches(3)
        store = build_crash_store(False)
        wal = WriteAheadLog(str(tmp_path / "wal"), fsync="off")
        store.attach_wal(wal)
        _drive(store, batches)
        wal.sync()
        wal.close()
        # a store from a DIFFERENT lineage: same schema, different
        # dictionary content at the same positions
        other = build_crash_store(False)
        other.dicts.services.encode("not-from-this-log")
        other.dicts.services.encode("nor-this")
        wal2 = WriteAheadLog(str(tmp_path / "wal"), fsync="off")
        with pytest.raises(WalReplayError, match="lineage"):
            replay_into(other, wal2, from_seq=0)
        wal2.close()


# ---------------------------------------------------------------------------
# Checkpoint slab integrity (rev 13)
# ---------------------------------------------------------------------------


class TestSlabIntegrity:
    def _saved(self, tmp_path):
        store = build_crash_store(False)
        _drive(store, crash_batches(2))
        path = str(tmp_path / "ckpt")
        checkpoint.save(store, path)
        return store, path

    def test_corrupt_slab_fails_fast_with_named_error(self, tmp_path):
        store, path = self._saved(tmp_path)
        state_file = os.path.join(path, "state.npz")
        data = dict(np.load(state_file))
        key = sorted(k for k in data
                     if data[k].size and data[k].dtype != bool)[0]
        arr = data[key].copy()
        flat = arr.reshape(-1)
        flat[0] = flat[0] ^ 1 if np.issubdtype(
            arr.dtype, np.integer) else flat[0] + 1.0
        data[key] = arr
        # rewrite a VALID npz with silently different content — the
        # rot the zip layer cannot catch, only the manifest CRC can
        from zipkin_tpu.checkpoint import _savez_fast

        _savez_fast(state_file, data)
        with pytest.raises(CorruptSlabError, match=key.split(".")[0]):
            checkpoint.load(path)

    def test_pre13_snapshot_without_crcs_still_loads(self, tmp_path):
        store, path = self._saved(tmp_path)
        meta_file = os.path.join(path, "meta.json")
        import json

        with open(meta_file) as f:
            meta = json.load(f)
        meta.pop("slab_crc32", None)
        meta.pop("clocks", None)
        meta["revision"] = 12
        with open(meta_file, "w") as f:
            json.dump(meta, f)
        rec = checkpoint.load(path)
        assert states_bitwise_equal(store.state, rec.state)


# ---------------------------------------------------------------------------
# Collector: ack-after-durable-append + quiesce ordering
# ---------------------------------------------------------------------------


class TestCollectorDurability:
    def test_ingest_durable_acks_after_durable_append(self, tmp_path):
        from zipkin_tpu.ingest.collector import Collector

        store = build_crash_store(False)
        wal = WriteAheadLog(str(tmp_path / "wal"), fsync="interval",
                            interval_s=0.01)
        store.attach_wal(wal)
        col = Collector(store)
        spans = crash_batches(1)[0]
        stored = col.ingest_durable(spans)
        assert stored == len(spans)
        # the ack barrier held: everything appended is fsynced
        assert wal.durable_seq == wal.last_seq >= 1
        tids = sorted({s.trace_id for s in spans})[:2]
        assert any(store.get_spans_by_trace_ids(tids))
        col.close()
        wal.close()

    def test_durable_entry_pushes_back_instead_of_false_ack(self):
        from zipkin_tpu.ingest.collector import Collector
        from zipkin_tpu.ingest.receiver import ResultCode, ScribeReceiver
        from zipkin_tpu.wal.log import WalDurabilityError

        store = build_crash_store(False)

        # a WAL whose durable frontier never advances (dead fsync)
        class _NeverDurable:
            last_seq = 0

            def append(self, payload):
                self.last_seq += 1
                return self.last_seq

            def wait_durable(self, seq, timeout=None):
                return False

        store.attach_wal(_NeverDurable())
        col = Collector(store)
        spans = crash_batches(1)[0][:4]
        with pytest.raises(WalDurabilityError):
            col.ingest_durable(spans)
        # and on the wire that is TRY_LATER (retry), never OK
        rx = ScribeReceiver(col.ingest_durable)
        import base64

        from zipkin_tpu.wire.thrift import span_to_bytes

        entries = [("zipkin",
                    base64.b64encode(span_to_bytes(s)).decode())
                   for s in spans]
        assert rx.log(entries) == ResultCode.TRY_LATER
        assert rx.stats["pushed_back"] == 1
        store.wal = None
        col.close()

    def test_flush_quiesces_in_durability_order(self, tmp_path):
        from zipkin_tpu.ingest.collector import Collector

        store = build_crash_store(False)
        calls = []
        store.drain_pipeline = lambda: calls.append("drain")
        store.seal_barrier = lambda: calls.append("seal")
        store.wal_sync = lambda: calls.append("fsync")
        col = Collector(store)
        col.flush()
        order = [c for c in calls]
        assert "drain" in order and "seal" in order and "fsync" in order
        assert (order.index("drain") < order.index("seal")
                < order.index("fsync"))
        # close() runs the same quiesce before store.close()
        calls.clear()
        store.close = lambda: calls.append("close")
        col.close()
        assert calls.index("fsync") < calls.index("close")
