"""Client instrumentation tests: B3 headers, tracer, WSGI middleware,
and the end-to-end instrumented-app → collector → query loop."""

import random

import pytest

from zipkin_tpu.client import (
    B3Headers,
    Tracer,
    ZipkinWSGIMiddleware,
)
from zipkin_tpu.ingest.collector import Collector
from zipkin_tpu.store.memory import InMemorySpanStore


class TestB3Headers:
    def test_parse_and_emit_roundtrip(self):
        b3 = B3Headers(trace_id=0xABC, span_id=0x123, parent_id=0x99,
                       sampled=True)
        parsed = B3Headers.parse(b3.emit())
        assert parsed == b3

    def test_parse_missing(self):
        assert B3Headers.parse({}) == B3Headers()

    def test_parse_garbage_ignored(self):
        parsed = B3Headers.parse({"X-B3-TraceId": "zz-not-hex"})
        assert parsed.trace_id is None

    def test_sampled_flag_forms(self):
        assert B3Headers.parse({"X-B3-Sampled": "1"}).sampled is True
        assert B3Headers.parse({"X-B3-Sampled": "0"}).sampled is False

    def test_negative_ids_roundtrip_as_unsigned_hex(self):
        b3 = B3Headers(trace_id=-5, span_id=-6)
        parsed = B3Headers.parse(b3.emit())
        assert parsed.trace_id == (-5) & (2**64 - 1)


class TestTracer:
    def test_server_span_continues_trace(self):
        got = []
        t = Tracer("api", got.extend, rng=random.Random(1))
        span = t.server_span("get /x", B3Headers(trace_id=7, span_id=8,
                                                 parent_id=6, sampled=True),
                             start_us=100, end_us=200)
        assert span is not None
        assert span.trace_id == 7 and span.id == 8 and span.parent_id == 6
        values = [a.value for a in span.annotations]
        assert values == ["sr", "ss"]
        assert got == [span]

    def test_starts_new_trace_without_headers(self):
        got = []
        t = Tracer("api", got.extend, rng=random.Random(2))
        span = t.server_span("x", B3Headers())
        assert span.trace_id > 0 and span.id > 0 and span.parent_id is None

    def test_upstream_not_sampled_wins(self):
        got = []
        t = Tracer("api", got.extend, sample_rate=1.0)
        assert t.server_span("x", B3Headers(sampled=False)) is None
        assert got == []

    def test_sample_rate_zero(self):
        t = Tracer("api", lambda s: None, sample_rate=0.0,
                   rng=random.Random(3))
        assert t.server_span("x", B3Headers()) is None


class TestWSGIMiddleware:
    def make_app(self):
        def app(environ, start_response):
            start_response("200 OK", [("Content-Type", "text/plain")])
            return [b"hello"]

        return app

    def test_instrumented_request_lands_in_store(self):
        store = InMemorySpanStore()
        collector = Collector(store)
        tracer = Tracer("front", collector.accept, rng=random.Random(4))
        app = ZipkinWSGIMiddleware(self.make_app(), tracer)
        environ = {
            "PATH_INFO": "/hello",
            "REQUEST_METHOD": "GET",
            "HTTP_X_B3_TRACEID": "ff",
            "HTTP_X_B3_SPANID": "ee",
            "HTTP_X_B3_SAMPLED": "1",
        }
        body = app(environ, lambda *a, **k: None)
        assert body == [b"hello"]
        collector.flush()
        spans = store.get_spans_by_trace_id(0xFF)
        assert len(spans) == 1
        s = spans[0]
        assert s.id == 0xEE and s.name == "get /hello"
        tags = {b.key: b.value for b in s.binary_annotations}
        assert tags["http.status"] == "200"
        assert tags["http.uri"] == "/hello"
        assert s.service_name == "front"
        collector.close()

    def test_response_echoes_b3_headers(self):
        """The response carries X-B3-TraceId/-SpanId matching the span
        actually recorded — the contract the devtools extension
        (web/extension/) and any caller correlating responses to
        traces relies on."""
        store = InMemorySpanStore()
        collector = Collector(store)
        tracer = Tracer("front", collector.accept, rng=random.Random(7))
        app = ZipkinWSGIMiddleware(self.make_app(), tracer)
        captured = {}

        def start_response(status, headers, exc_info=None):
            captured["headers"] = dict(headers)

        # Continued trace: echoed ids == the incoming ids.
        app({"PATH_INFO": "/x", "REQUEST_METHOD": "GET",
             "HTTP_X_B3_TRACEID": "ab", "HTTP_X_B3_SPANID": "cd",
             "HTTP_X_B3_SAMPLED": "1"}, start_response)
        assert captured["headers"]["X-B3-TraceId"] == "ab"
        assert captured["headers"]["X-B3-SpanId"] == "cd"
        assert captured["headers"]["X-B3-Sampled"] == "1"
        # Fresh trace: echoed id is the one the recorded span carries.
        app({"PATH_INFO": "/y", "REQUEST_METHOD": "GET"},
            start_response)
        tid = int(captured["headers"]["X-B3-TraceId"], 16)
        collector.flush()
        spans = store.get_spans_by_trace_id(tid)
        assert [s.name for s in spans if s.name == "get /y"]
        # Unsampled: NO trace id echoed (it would be a dead link for
        # the extension) — only the sampled=0 marker.
        app({"PATH_INFO": "/z", "REQUEST_METHOD": "GET",
             "HTTP_X_B3_SAMPLED": "0"}, start_response)
        assert "X-B3-TraceId" not in captured["headers"]
        assert captured["headers"]["X-B3-Sampled"] == "0"
        collector.close()


class TestNestedMiddlewares:
    def test_nested_middleware_emits_single_b3_header_set(self):
        """Two stacked ZipkinWSGIMiddlewares (an app composed of traced
        sub-apps) must not emit duplicate/conflicting X-B3-* response
        headers: the OUTER middleware resolved the request's ids, so
        its echo wins and pre-existing X-B3-* entries are filtered
        case-insensitively (ADVICE r5 — the devtools panel links
        whichever header it reads first)."""
        import random

        from zipkin_tpu.client import Tracer, ZipkinWSGIMiddleware

        def app(environ, start_response):
            # An app that already emitted its own (conflicting) B3
            # echo, lowercase to exercise case-insensitive filtering.
            start_response("200 OK", [
                ("Content-Type", "text/plain"),
                ("x-b3-traceid", "dead"),
                ("X-B3-SpanId", "beef"),
            ])
            return [b"ok"]

        inner = ZipkinWSGIMiddleware(
            app, Tracer("inner", lambda spans: None,
                        rng=random.Random(1)))
        outer = ZipkinWSGIMiddleware(
            inner, Tracer("outer", lambda spans: None,
                          rng=random.Random(2)))
        captured = {}

        def start_response(status, headers, exc_info=None):
            captured["headers"] = headers

        outer({"PATH_INFO": "/n", "REQUEST_METHOD": "GET",
               "HTTP_X_B3_TRACEID": "ab", "HTTP_X_B3_SPANID": "cd",
               "HTTP_X_B3_SAMPLED": "1"}, start_response)
        names = [k.lower() for k, _ in captured["headers"]
                 if k.lower().startswith("x-b3-")]
        # Exactly one value per B3 header, no duplicates.
        assert sorted(names) == sorted(set(names))
        by_name = {k.lower(): v for k, v in captured["headers"]}
        assert by_name["x-b3-traceid"] == "ab"
        assert by_name["x-b3-spanid"] == "cd"
        assert by_name["x-b3-sampled"] == "1"
