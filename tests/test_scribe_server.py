"""Raw-TCP framed-thrift scribe endpoint: a real client socket → spans
land in the store (reference: ScribeSpanReceiver.scala:69-141)."""

import base64

import pytest

from zipkin_tpu.ingest.collector import Collector
from zipkin_tpu.ingest.receiver import ResultCode, ScribeReceiver
from zipkin_tpu.ingest.scribe_server import (
    ScribeClient,
    ScribeServer,
    decode_log_reply,
    encode_log_call,
    handle_call,
)
from zipkin_tpu.models.span import Annotation, Endpoint, Span
from zipkin_tpu.store.memory import InMemorySpanStore
from zipkin_tpu.wire.thrift import ThriftError, span_to_bytes

EP = Endpoint(0x0A000001, 80, "svc")


def make_span(tid, sid):
    return Span(trace_id=tid, name="op", id=sid,
                annotations=(Annotation(10, "sr", EP),
                             Annotation(20, "ss", EP)))


def entry_for(span):
    return ("zipkin", base64.b64encode(span_to_bytes(span)).decode())


class TestFrameCodec:
    def test_roundtrip_call_reply(self):
        store = InMemorySpanStore()
        collector = Collector(store, max_queue=10, concurrency=1)
        rx = ScribeReceiver(collector.accept)
        frame = encode_log_call([entry_for(make_span(1, 1))], seqid=7)
        reply = handle_call(rx, frame[4:])  # strip length prefix
        assert decode_log_reply(reply) == ResultCode.OK
        collector.flush()
        assert store.get_spans_by_trace_ids([1])
        collector.close()

    def test_unknown_method_gets_exception(self):
        rx = ScribeReceiver(lambda spans: None)
        frame = encode_log_call([], seqid=1)
        # Rewrite method name "Log" -> "Nop" (same length).
        bad = frame[4:].replace(b"Log", b"Nop", 1)
        reply = handle_call(rx, bad)
        with pytest.raises(ThriftError):
            decode_log_reply(reply)


class TestTcpEndToEnd:
    def test_client_to_store_over_socket(self):
        store = InMemorySpanStore()
        collector = Collector(store, max_queue=100, concurrency=2)
        rx = ScribeReceiver(collector.accept)
        server = ScribeServer(rx, host="127.0.0.1", port=0)
        server.serve_in_thread()
        host, port = server.server_address
        client = ScribeClient(host, port)
        try:
            spans = [make_span(i, 1) for i in range(1, 6)]
            code = client.log([entry_for(s) for s in spans])
            assert code == ResultCode.OK
            collector.flush()
            for s in spans:
                got = store.get_spans_by_trace_ids([s.trace_id])
                assert got and got[0][0].trace_id == s.trace_id
            assert rx.stats["received"] == 5
        finally:
            client.close()
            server.shutdown()
            collector.close()

    def test_pushback_try_later(self):
        import threading

        store = InMemorySpanStore()
        gate = threading.Event()
        collector = Collector(store, max_queue=1, concurrency=1)
        orig_apply = store.apply
        store.apply = lambda spans: (gate.wait(5), orig_apply(spans))[1]
        rx = ScribeReceiver(collector.accept)
        server = ScribeServer(rx, host="127.0.0.1", port=0)
        server.serve_in_thread()
        host, port = server.server_address
        client = ScribeClient(host, port)
        try:
            codes = set()
            for i in range(20):
                codes.add(client.log([entry_for(make_span(100 + i, 1))]))
            assert ResultCode.TRY_LATER in codes  # queue filled -> pushback
            gate.set()
        finally:
            client.close()
            server.shutdown()
            gate.set()
            collector.close()
