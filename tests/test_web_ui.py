"""Structural checks for the single-file SPA.

No JS engine ships in this environment, so the page can't be executed
here; these tests pin the structural contract instead — the DOM ids
the script wires, the API routes it calls (each cross-checked against
the server's route table), and bracket/template-literal balance of the
inline script (the class of breakage a bad edit actually produces).
The three core journeys (find → open → inspect span; dependencies;
aggregates) are driven live against the daemon during verification
(see .claude/skills/verify).
"""

import re
from pathlib import Path

import pytest

HTML = Path(__file__).parent.parent.joinpath(
    "zipkin_tpu", "web", "index.html").read_text()


def test_views_and_nav_ids_pair_up():
    views = set(re.findall(r'id="view-(\w+)"', HTML))
    navs = set(re.findall(r'id="nav-(\w+)"', HTML))
    assert views == navs == {"traces", "deps", "agg"}


def test_span_panel_and_filter_wiring_present():
    # The spanPanel.js / traceFilters.js role markers (VERDICT r4 #4).
    for marker in ("renderSpanPanel", 'id="span-panel"', "wf-filter",
                   "binaryAnnotations", "loadAggregates",
                   "loadServiceAggregates"):
        assert marker in HTML, marker


def test_api_routes_used_by_ui_exist_on_server():
    from zipkin_tpu.api import server as srv

    src = Path(srv.__file__).read_text()
    called = set(re.findall(r'"(/api/[a-z_]+)[?"]', HTML))
    assert {"/api/services", "/api/query", "/api/spans",
            "/api/dependencies", "/api/quantiles",
            "/api/top_annotations",
            "/api/top_kv_annotations"} <= called
    for route in called:
        assert route in src, f"UI calls {route} but server lacks it"


def _assert_js_balanced(src: str):
    src = src.replace('/[&<>"]/g', "RX")  # the esc() regex literal
    stack, mode = [], []
    i, line, err = 0, 1, None
    while i < len(src) and not err:
        c = src[i]
        if c == "\n":
            line += 1
        top = mode[-1] if mode else None
        if top in ("'", '"'):
            if c == "\\":
                i += 2
                continue
            if c == top:
                mode.pop()
            elif c == "\n":
                err = f"line {line}: newline in string"
        elif top == "`":
            if c == "\\":
                i += 2
                continue
            if c == "`":
                mode.pop()
            elif c == "$" and src[i + 1:i + 2] == "{":
                stack.append("${")
                mode.append("e")
                i += 2
                continue
        else:
            if c in "'\"`":
                mode.append(c)
            elif c == "/" and src[i + 1:i + 2] == "/":
                while i < len(src) and src[i] != "\n":
                    i += 1
                continue
            elif c in "([{":
                stack.append(c)
            elif c in ")]}":
                want = {")": "(", "]": "[", "}": "{"}[c]
                if c == "}" and stack and stack[-1] == "${":
                    stack.pop()
                    mode.pop()
                elif not stack or stack[-1] != want:
                    err = f"line {line}: unmatched {c}"
                else:
                    stack.pop()
        i += 1
    assert not err and not stack and not mode, (err, stack[-3:], mode)


def test_inline_script_brackets_and_templates_balance():
    m = re.search(r"<script>(.*)</script>", HTML, re.S)
    assert m, "no inline script"
    _assert_js_balanced(m.group(1))


def test_trace_deep_link_wiring():
    # The #trace= deep link ties the SPA, the middleware's echoed
    # X-B3-TraceId headers, and the devtools extension together.
    assert "openFromHash" in HTML
    assert "#trace=" in HTML


EXT = Path(__file__).parent.parent.joinpath("zipkin_tpu", "web",
                                            "extension")


class TestExtension:
    """Structural checks for the devtools extension (the reference's
    zipkin-browser-extension role, rebuilt on devtools.network — no
    browser ships in this environment, so the panel can't execute
    here; the manifest contract and script structure are pinned)."""

    def test_manifest_parses_and_references_exist(self):
        import json

        mf = json.loads(EXT.joinpath("manifest.json").read_text())
        assert mf["manifest_version"] == 3
        assert EXT.joinpath(mf["devtools_page"]).exists()
        # The devtools page loads devtools.js which loads panel.html.
        assert "devtools.js" in EXT.joinpath("devtools.html").read_text()
        assert "panel.html" in EXT.joinpath("devtools.js").read_text()
        assert "panel.js" in EXT.joinpath("panel.html").read_text()

    def test_panel_watches_the_middleware_contract(self):
        js = EXT.joinpath("panel.js").read_text()
        assert "X-B3-TraceId" in js          # the echoed header
        assert "#trace=" in js               # the SPA deep link
        assert "onRequestFinished" in js     # devtools.network API
        _assert_js_balanced(js)
        _assert_js_balanced(EXT.joinpath("devtools.js").read_text())
