"""Ingest runtime tests: thrift wire roundtrip, queue backpressure,
scribe receiver decode + TRY_LATER, collector pipeline with sampling."""

import threading
import time

import pytest

from zipkin_tpu.ingest import (
    Collector,
    ItemQueue,
    JsonReceiver,
    QueueFullException,
    ResultCode,
    ScribeReceiver,
)
from zipkin_tpu.ingest.receiver import span_from_json, span_to_json
from zipkin_tpu.models.span import (
    Annotation,
    AnnotationType,
    BinaryAnnotation,
    Endpoint,
    Span,
)
from zipkin_tpu.store.memory import InMemorySpanStore
from zipkin_tpu.wire.thrift import (
    scribe_message_to_span,
    span_from_bytes,
    span_to_bytes,
    span_to_scribe_message,
    spans_from_bytes,
)

EP = Endpoint(0x7F000001, 8080, "some-service")

SPAN = Span(
    trace_id=-(2**62) + 7,
    name="get /widgets",
    id=12345,
    parent_id=-99,
    annotations=(
        Annotation(1_700_000_000_000_000, "cs", EP),
        Annotation(1_700_000_000_500_000, "cr", EP),
        Annotation(1_700_000_000_100_000, "custom", None),
    ),
    binary_annotations=(
        BinaryAnnotation("http.uri", "/widgets", AnnotationType.STRING, EP),
        BinaryAnnotation("blob", b"\x00\xff", AnnotationType.BYTES, None),
        BinaryAnnotation("count", 42, AnnotationType.I32, None),
        BinaryAnnotation("ok", True, AnnotationType.BOOL, None),
    ),
    debug=True,
)


class TestThriftWire:
    def test_roundtrip(self):
        data = span_to_bytes(SPAN)
        got, pos = span_from_bytes(data)
        assert pos == len(data)
        assert got == SPAN

    def test_concatenated_spans(self):
        bare = Span(trace_id=1, name="x", id=2)
        data = span_to_bytes(SPAN) + span_to_bytes(bare)
        assert spans_from_bytes(data) == [SPAN, bare]

    def test_scribe_base64_roundtrip(self):
        msg = span_to_scribe_message(SPAN)
        assert scribe_message_to_span(msg) == SPAN

    def test_unknown_fields_skipped(self):
        # Append an unknown i32 field id 99 before the stop byte.
        import struct

        data = span_to_bytes(SPAN)
        patched = data[:-1] + struct.pack(">bhi", 8, 99, 7) + b"\x00"
        got, _ = span_from_bytes(patched)
        assert got == SPAN

    def test_truncated_raises(self):
        from zipkin_tpu.wire.thrift import ThriftError

        with pytest.raises(ThriftError):
            span_from_bytes(span_to_bytes(SPAN)[:10])


class TestJson:
    def test_roundtrip(self):
        assert span_from_json(span_to_json(SPAN)) == SPAN

    def test_hex_ids_accepted(self):
        d = span_to_json(Span(trace_id=255, name="x", id=16))
        d["traceId"], d["id"] = "ff", "10"
        got = span_from_json(d)
        assert got.trace_id == 255 and got.id == 16


class TestItemQueue:
    def test_processes_items(self):
        seen = []
        q = ItemQueue(seen.append, max_size=10, concurrency=2)
        for i in range(5):
            q.add(i)
        q.join()
        assert sorted(seen) == [0, 1, 2, 3, 4]
        assert q.processed == 5

    def test_queue_full_raises(self):
        gate = threading.Event()
        q = ItemQueue(lambda _: gate.wait(5), max_size=2, concurrency=1)
        q.add(1)
        time.sleep(0.1)  # let the worker pick up item 1 and block
        q.add(2)
        q.add(3)
        with pytest.raises(QueueFullException):
            q.add(4)
        gate.set()
        q.join()

    def test_errors_counted_not_fatal(self):
        def boom(i):
            if i == 1:
                raise RuntimeError("nope")

        q = ItemQueue(boom, max_size=10, concurrency=1)
        q.add(0)
        q.add(1)
        q.add(2)
        q.join()
        assert q.errors == 1 and q.processed == 2

    def test_close_drains(self):
        seen = []
        q = ItemQueue(seen.append, max_size=100, concurrency=3)
        for i in range(50):
            q.add(i)
        q.close()
        assert len(seen) == 50
        with pytest.raises(QueueFullException):
            q.add(99)

    def test_depth_and_drop_gauges_under_enqueue_pressure(self):
        """The telemetry registry's queue depth gauge and rejected
        counter track a full buffer exactly (drop-rate observable)."""
        from zipkin_tpu import obs

        reg = obs.Registry()
        gate = threading.Event()
        q = ItemQueue(lambda _: gate.wait(10), max_size=2,
                      concurrency=1, registry=reg)
        try:
            q.add("a")  # worker picks this up and blocks
            deadline = time.monotonic() + 5
            while q.active_workers < 1 and time.monotonic() < deadline:
                time.sleep(0.005)
            for _ in range(2):  # fill behind the blocked worker
                try:
                    q.add("b")
                except QueueFullException:
                    break
            rejections = 0
            for _ in range(3):
                try:
                    q.add("c")
                except QueueFullException:
                    rejections += 1
            assert rejections >= 1
            d = reg.as_dict()
            assert d["zipkin_queue_depth"] >= 2
            assert d["zipkin_queue_rejected_total"] == rejections
            assert d["zipkin_queue_active_workers"] == 1
        finally:
            gate.set()
            q.close(timeout=5)
        done = reg.as_dict()
        assert done["zipkin_queue_processed_total"] == \
            done["zipkin_queue_enqueued_total"]

    def test_concurrent_worker_counters_exact(self):
        """processed/errors ride locked registry counters now; the old
        unlocked += lost increments under concurrent workers."""
        def maybe_boom(i):
            if i % 10 == 0:
                raise RuntimeError("boom")

        q = ItemQueue(maybe_boom, max_size=500, concurrency=8)
        for i in range(400):
            q.add(i)
        q.join()
        assert q.errors == 40
        assert q.processed == 360


class TestScribeReceiver:
    def test_decode_and_process(self):
        got = []
        r = ScribeReceiver(got.extend)
        code = r.log([("zipkin", span_to_scribe_message(SPAN))])
        assert code is ResultCode.OK
        assert got == [SPAN]

    def test_category_whitelist(self):
        got = []
        r = ScribeReceiver(got.extend)
        assert r.log([("other", span_to_scribe_message(SPAN))]) is ResultCode.OK
        assert got == [] and r.stats["ignored"] == 1

    def test_bad_payload_counted(self):
        got = []
        r = ScribeReceiver(got.extend)
        r.log([("zipkin", "!!!not-thrift!!!")])
        assert r.stats["bad"] == 1 and got == []

    def test_try_later_on_queue_full(self):
        def full(_spans):
            raise QueueFullException("full")

        r = ScribeReceiver(full)
        code = r.log([("zipkin", span_to_scribe_message(SPAN))])
        assert code is ResultCode.TRY_LATER
        assert r.stats["pushed_back"] == 1


class TestCollector:
    def test_end_to_end_scribe_to_store(self):
        store = InMemorySpanStore()
        col = Collector(store)
        recv = ScribeReceiver(col.accept)
        recv.log([("zipkin", span_to_scribe_message(SPAN))])
        col.flush()
        assert store.get_spans_by_trace_id(SPAN.trace_id) == [SPAN]
        col.close()

    def test_sampling_drops_but_debug_passes(self):
        from zipkin_tpu.sampler.core import Sampler

        store = InMemorySpanStore()
        col = Collector(store, sampler=Sampler(0.0))
        debug_span = Span(trace_id=5, name="d", id=1, debug=True)
        plain_span = Span(trace_id=6, name="p", id=2)
        col.accept([debug_span, plain_span])
        col.flush()
        assert store.traces_exist([5, 6]) == {5}
        assert col.spans_dropped == 1

    def test_adaptive_control_tick_moves_rate(self):
        from zipkin_tpu.sampler.adaptive import AdaptiveConfig

        store = InMemorySpanStore()
        cfg = AdaptiveConfig(
            target_store_rate=60.0, update_freq_s=1.0, window_s=10.0,
            sufficient_window_s=3.0, outlier_window_s=2.0,
        )
        col = Collector(store, adaptive=cfg)
        now = 1000.0
        # Feed ~40x the target store rate for a while.
        for tick in range(12):
            spans = [
                Span(trace_id=tick * 1000 + i, name="s", id=1)
                for i in range(40)
            ]
            col.accept(spans)
            col.flush()
            col.control_tick(now)
            now += 1.0
        assert col.sampler.rate < 1.0


class TestKafkaSink:
    def _sink(self, **kw):
        from zipkin_tpu.ingest.kafka import KafkaSpanSink

        sent = []
        sink = KafkaSpanSink(lambda topic, value: sent.append((topic, value)),
                             **kw)
        return sink, sent

    def test_publishes_thrift_spans_roundtrip(self):
        from zipkin_tpu.ingest.kafka import KafkaSpanReceiver
        from zipkin_tpu.tracegen import generate_traces
        from zipkin_tpu.wire.thrift import spans_from_bytes

        spans = [s for t in generate_traces(n_traces=5, max_depth=3)
                 for s in t]
        sink, sent = self._sink()
        sink.apply(spans)
        assert sink.stats["published"] == len(spans)
        assert all(topic == "zipkin" for topic, _ in sent)
        # The published bytes ARE the receiver's wire format: feed them
        # back through KafkaSpanReceiver and get the same spans.
        got = []
        rx = KafkaSpanReceiver(got.extend, [[v for _, v in sent]])
        rx.run()
        assert got == spans

    def test_batch_mode_one_message(self):
        from zipkin_tpu.tracegen import generate_traces
        from zipkin_tpu.wire.thrift import spans_from_bytes

        spans = [s for t in generate_traces(n_traces=3, max_depth=3)
                 for s in t]
        sink, sent = self._sink(batch=True)
        sink.apply(spans)
        assert len(sent) == 1
        assert spans_from_bytes(sent[0][1]) == spans

    def test_producer_errors_counted_not_raised(self):
        from zipkin_tpu.ingest.kafka import KafkaSpanSink
        from zipkin_tpu.tracegen import generate_traces

        def boom(topic, value):
            raise RuntimeError("broker down")

        sink = KafkaSpanSink(boom)
        spans = [s for t in generate_traces(n_traces=2, max_depth=2)
                 for s in t]
        sink.apply(spans)  # must not raise
        assert sink.stats["errors"] == len(spans)

    def test_fanout_member(self):
        from zipkin_tpu.store.base import FanoutWriteSpanStore
        from zipkin_tpu.store.memory import InMemorySpanStore
        from zipkin_tpu.tracegen import generate_traces

        sink, sent = self._sink()
        mem = InMemorySpanStore()
        fan = FanoutWriteSpanStore(mem, sink)
        spans = [s for t in generate_traces(n_traces=2, max_depth=2)
                 for s in t]
        fan.apply(spans)
        fan.set_time_to_live(spans[0].trace_id, 99.0)
        assert len(mem.spans) == len(spans) and len(sent) == len(spans)
        fan.close()

    def test_async_producer_future_errors(self):
        """kafka-python-style async producers report delivery on the
        returned future from an IO thread; a down broker must count as
        errors, not phantom publishes."""
        from zipkin_tpu.ingest.kafka import KafkaSpanSink
        from zipkin_tpu.tracegen import generate_traces

        class FakeFuture:
            def __init__(self, ok):
                self.ok = ok

            def add_callback(self, fn):
                if self.ok:
                    fn(None)

            def add_errback(self, fn):
                if not self.ok:
                    fn(RuntimeError("broker down"))

        outcomes = iter([True, False, True])
        sink = KafkaSpanSink(lambda t, v: FakeFuture(next(outcomes)))
        spans = [s for t in generate_traces(n_traces=3, max_depth=1)
                 for s in t][:3]
        for s in spans:
            sink.apply([s])
        assert sink.stats["published"] == 2
        assert sink.stats["errors"] == 1
        # Uncompressed sink: wire bytes are the raw payload bytes.
        assert sink.stats["bytes_wire"] == sink.stats["bytes_raw"] > 0


def test_kafka_record_value_stream_adapts_both_shapes():
    """The documented client adapter: kafka-python style records (carry
    .value bytes) and raw byte iterables both drain identically."""
    from types import SimpleNamespace

    from zipkin_tpu.ingest.kafka import record_value_stream

    raw = [b"a", b"b"]
    recs = [SimpleNamespace(value=b"a"), SimpleNamespace(value=b"b")]
    assert list(record_value_stream(raw)) == raw
    assert list(record_value_stream(recs)) == raw


def test_connect_kafka_python_degrades_clearly_without_client():
    """No kafka lib ships here: the real-client constructor must fail
    with the contract message, not an obscure ImportError downstream."""
    import pytest

    from zipkin_tpu.ingest.kafka import connect_kafka_python

    try:
        import kafka  # noqa: F401
        pytest.skip("kafka-python unexpectedly present")
    except ImportError:
        pass
    with pytest.raises(RuntimeError, match="integration contract"):
        connect_kafka_python(lambda spans: None, "localhost:9092")
