"""Span model unit tests (reference: zipkin-common SpanTest/AnnotationTest/EndpointTest)."""

import pytest

from zipkin_tpu.models.span import (
    Annotation,
    BinaryAnnotation,
    Endpoint,
    Span,
    merge_by_span_id,
)

EP_CLIENT = Endpoint(1, 80, "Client")
EP_SERVER = Endpoint(2, 80, "server")


def make_rpc_span():
    return Span(
        trace_id=10,
        name="get",
        id=20,
        parent_id=None,
        annotations=(
            Annotation(100, "cs", EP_CLIENT),
            Annotation(150, "sr", EP_SERVER),
            Annotation(190, "ss", EP_SERVER),
            Annotation(200, "cr", EP_CLIENT),
        ),
    )


def test_service_name_prefers_server_side():
    assert make_rpc_span().service_name == "server"


def test_service_name_falls_back_to_client():
    span = Span(1, "x", 2, annotations=(Annotation(5, "cs", EP_CLIENT),))
    assert span.service_name == "Client"


def test_service_names_lowercased():
    assert make_rpc_span().service_names == {"client", "server"}


def test_duration_and_first_last():
    span = make_rpc_span()
    assert span.first_timestamp == 100
    assert span.last_timestamp == 200
    assert span.duration == 100


def test_duration_none_without_annotations():
    assert Span(1, "x", 2).duration is None


def test_is_valid_rejects_duplicate_core_annotations():
    span = make_rpc_span()
    assert span.is_valid()
    bad = Span(
        1, "x", 2, annotations=(Annotation(1, "cs", None), Annotation(2, "cs", None))
    )
    assert not bad.is_valid()


def test_merge_combines_halves():
    client = Span(
        1,
        "get",
        2,
        annotations=(Annotation(100, "cs", EP_CLIENT), Annotation(200, "cr", EP_CLIENT)),
        binary_annotations=(BinaryAnnotation("k", b"v"),),
    )
    server = Span(
        1,
        "",
        2,
        annotations=(Annotation(150, "sr", EP_SERVER), Annotation(190, "ss", EP_SERVER)),
        debug=True,
    )
    merged = server.merge(client)
    assert merged.name == "get"  # empty name replaced
    assert len(merged.annotations) == 4
    assert len(merged.binary_annotations) == 1
    assert merged.debug


def test_merge_rejects_mismatched_ids():
    with pytest.raises(ValueError):
        Span(1, "a", 2).merge(Span(1, "a", 3))


def test_merge_by_span_id():
    a = Span(1, "a", 2, annotations=(Annotation(1, "cs", EP_CLIENT),))
    b = Span(1, "", 2, annotations=(Annotation(2, "sr", EP_SERVER),))
    c = Span(1, "c", 3, annotations=(Annotation(3, "cs", EP_CLIENT),))
    merged = merge_by_span_id([a, b, c])
    assert len(merged) == 2
    assert len(merged[0].annotations) == 2


def test_is_client_side():
    assert Span(1, "x", 2, annotations=(Annotation(1, "cs", None),)).is_client_side()
    assert not Span(1, "x", 2, annotations=(Annotation(1, "sr", None),)).is_client_side()


def test_endpoint_ipv4_str():
    assert Endpoint(0x7F000001, 80, "s").ipv4_str() == "127.0.0.1"
